"""Decoder-only transformer language model (GPT-style).

Scope beyond the reference (vision-only — ResNet on ImageNet,
src/ddp_tasks.jl:275): this family exists to make the framework's
long-context machinery first-class on a model that actually has a long
sequence axis.  The design choices are TPU-first:

* **Pluggable core attention** (the ViT pattern, models/vit.py): pass
  ``attn_fn=make_ring_attention(mesh, causal=True)`` and the SAME module
  trains sequence-parallel over a ``seq`` mesh axis, or
  ``ops.pallas_attention.flash_attention`` for the fused kernel — the
  default is the XLA-fused ``dot_product_attention(causal=True)``.
* **RoPE positions** computed on the global token axis — applied before
  the attention call, so under GSPMD sequence sharding every shard still
  rotates by its true global position (no per-shard offset bookkeeping).
* **Pre-LN blocks, bf16 compute, f32 logits** — the residual stream and
  softmax/CE stay accurate while matmuls ride the MXU in bf16.
* **Tied input/output embeddings** by default (halves embedding memory —
  the vocab table is usually the largest single tensor at small scale).

``lm_loss_fn`` adapts the model to the framework's loss signature, so
every training path — DP (``make_train_step``), FSDP, TP, SP — applies
unchanged: the batch is ``{"tokens": int32 [B, T]}`` and the loss is
next-token cross-entropy.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..mesh import EXPERT_AXIS, PIPE_AXIS
from ..ops.attention import dot_product_attention
from .common import maybe_remat

__all__ = [
    "TransformerLM",
    "lm_loss_fn",
    "next_token_loss",
    "rope",
    "generate",
    "make_decode_cache",
    "lm_pp",
    "MoEDecoderBlock",
    "moe_expert_fn",
    "lm_moe_specs",
    "lm_tiny",
    "lm_small",
    "lm_medium",
]

AttnFn = Callable[[jax.Array, jax.Array, jax.Array], jax.Array]


def rope(x: jax.Array, positions: jax.Array, base: float = 10000.0) -> jax.Array:
    """Rotary position embedding on ``x``: [B, T, H, D] with D even.

    ``positions``: [T] (or [B, T]) global token indices.  Pairs feature
    ``2i`` with ``2i+1`` and rotates by ``pos / base^(2i/D)`` — relative
    offsets become phase differences, so attention scores depend only on
    key/query distance.  Computed in f32 and cast back (bf16 phase
    accumulation loses precision at long context).
    """
    d = x.shape[-1]
    assert d % 2 == 0, "rope needs an even head dim"
    inv_freq = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., T, D/2]
    # broadcast over batch/head axes: positions [T] -> [1, T, 1, D/2]
    while ang.ndim < x.ndim:
        ang = ang[None] if ang.ndim < x.ndim - 1 else ang[..., None, :]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., 0::2], x32[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


#: KV-cache storage scenarios: full-precision, int8 (4x smaller than
#: f32, 2x smaller than bf16), fp8 e4m3 (same bytes as int8, no rounding
#: step — hardware-dependent, stubbed behind dtype availability)
KV_QUANTS = ("none", "int8", "fp8")

#: ``valid_len`` cache sentinel meaning "every write is real" — decode
#: steps and unpadded prefills (models.generate) run ungated.  The
#: serving engine sets ``valid_len`` to the REAL token count per padded
#: prefill/chunk call (a dynamic operand, pure cache DATA), so pad
#: positions never write into the windowed ring — which is what lets
#: the ring be sized exactly ``sinks + window``, no ``ring_slack``
#: over-allocation.  2**30 keeps ``cursor + VALID_UNGATED`` inside
#: int32 for any reachable cursor.
VALID_UNGATED = 2 ** 30


def _kv_store_dtype(kv_quant: str):
    """The cache leaf dtype for a quant scenario (None = model dtype)."""
    if kv_quant == "int8":
        return jnp.int8
    if kv_quant == "fp8":
        dt = getattr(jnp, "float8_e4m3fn", None)
        if dt is None:
            raise ValueError(
                "kv_quant='fp8' needs jnp.float8_e4m3fn, which this "
                "jax/jaxlib build does not provide — use kv_quant='int8'")
        return dt
    return None


def quantize_kv(x: jax.Array, kv_quant: str):
    """Quantize new K/V rows for cache storage: per-row-per-head absmax
    scaling over the head dim.  ``x`` [..., H, D] → ``(stored [..., H, D]
    in the storage dtype, scale [..., H] f32)``.  The scale rides in the
    cache next to its rows (dense: per slot row; paged: per pool block
    row), so every read path — XLA dequant-after-gather or the decode
    kernel's in-kernel dequant — sees the same numbers."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    if kv_quant == "int8":
        scale = jnp.maximum(amax, 1e-12) / 127.0
        q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    else:  # fp8 e4m3: max normal 448
        scale = jnp.maximum(amax, 1e-12) / 448.0
        q = xf / scale[..., None]
    return q.astype(_kv_store_dtype(kv_quant)), scale


def dequantize_kv(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Invert :func:`quantize_kv` into the model's compute dtype."""
    return (q.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]
            ).astype(dtype)


def _norm_layer(kind: str, dtype, name: Optional[str] = None,
                eps: float = 1e-6):
    """``layernorm`` (GPT-2 style, default) or ``rmsnorm`` (Llama
    style: no mean-centering, no bias — one fewer reduction per norm on
    the VPU and a smaller param tree).  ``eps`` matters for weight
    interop: HF GPT-2 uses 1e-5 where flax defaults to 1e-6."""
    if kind == "layernorm":
        return nn.LayerNorm(dtype=dtype, name=name, epsilon=eps)
    if kind == "rmsnorm":
        return nn.RMSNorm(dtype=dtype, name=name, epsilon=eps)
    raise ValueError(f"unknown norm {kind!r} (layernorm|rmsnorm)")


class CausalSelfAttention(nn.Module):
    """QKV projection + RoPE + pluggable causal core + output projection.

    ``decode=True`` switches to single-token autoregressive mode with a
    KV cache: the cache buffers are created at ``init`` time (which
    traces the full target length, fixing the static cache shape — no
    dynamic shapes under jit), and each ``apply`` writes the new K/V at
    ``cache_index`` via ``dynamic_update_slice`` and attends the one
    query against the filled prefix.  O(T) per generated token instead
    of O(T²) re-prefill.
    """

    num_heads: int
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[AttnFn] = None
    use_rope: bool = True
    decode: bool = False
    num_kv_heads: Optional[int] = None  # GQA: None/num_heads → MHA
    window: Optional[int] = None  # sliding-window attention (causal)
    sinks: int = 0  # StreamingLLM attention sinks (first `sinks` keys)
    # continuous-batching mode (serve/engine.py): each batch row is an
    # independent request SLOT with its own cache cursor — cache_index
    # becomes [B] and the windowed ring's slot_pos becomes [B, cache_len],
    # so slots at different depths decode together in ONE fixed-shape
    # compiled step.  Only single-token steps are supported post-init
    # (prefill runs through the scalar-index path on a batch-1 model and
    # the engine splices the result into the slot).
    slot_decode: bool = False
    # LEGACY extra windowed-ring capacity beyond sinks+window.  Padded
    # prefill used to need slack >= the largest pad run so a pad write
    # could not evict an in-band key; the dynamic ``valid_len`` cache
    # operand (see VALID_UNGATED) now gates pad positions out of the
    # ring write entirely, so the serving engine runs with slack 0 and
    # an exactly-sized ring.  The knob is kept for callers that want a
    # larger retention ring: band semantics are untouched — a larger
    # ring only RETAINS more, and retained out-of-band keys are
    # mask-excluded anyway.
    ring_slack: int = 0
    # paged KV cache (serve/engine.py layout="paged"): instead of one
    # contiguous [B, rows] cache per layer, K/V live in a shared pool of
    # ``kv_blocks`` fixed-size blocks ([blocks, kv_block_size, hkv, dh])
    # and each batch row carries a page table of int32 block ids.  The
    # indirection is DATA, never shape (arXiv:1810.09868's full-program
    # lesson): page-table updates feed the same compiled program, so
    # HBM scales with live tokens while the ONE-decode-compile invariant
    # holds.  A -1 page-table entry means "unallocated": reads through
    # it are mask-excluded, writes are dropped — which is also what
    # parks a freed slot safely.  0 = dense (the default layout).
    kv_block_size: int = 0
    kv_blocks: int = 0
    # decode attention implementation: "xla" (mask/gather over the cache,
    # the reference path) or "pallas" (ops/pallas_decode.py flash-decode
    # kernel — single-token steps only; prefill chunks stay XLA).  The
    # kernel consumes every cache layout natively (cursor block-skip,
    # windowed ring + sinks via slot_pos, paged page-table walk) and
    # falls back to an XLA rendering of the same block-walk schedule on
    # non-TPU backends (interpreter mode covers CPU kernel tests).
    attention_impl: str = "xla"
    # KV-cache storage quantization: "none" | "int8" | "fp8" — stored
    # values carry per-row-per-head scales in sibling cache leaves
    # (cached_k_scale/cached_v_scale); every attention read (XLA gather
    # or the decode kernel) dequantizes the SAME stored numbers, so all
    # impls agree token-for-token at a given quant setting.
    kv_quant: str = "none"

    @nn.compact
    def __call__(self, x):
        if self.attention_impl not in ("xla", "pallas"):
            raise ValueError(
                f"unknown attention_impl {self.attention_impl!r} "
                "(xla|pallas)")
        if self.kv_quant not in KV_QUANTS:
            raise ValueError(
                f"unknown kv_quant {self.kv_quant!r} ({'|'.join(KV_QUANTS)})")
        if self.kv_quant != "none":
            if not self.decode:
                raise ValueError(
                    "kv_quant quantizes the decode KV cache; build the "
                    "model with decode=True (the training forward has no "
                    "cache to quantize)")
            _kv_store_dtype(self.kv_quant)  # fp8 availability check
        if self.slot_decode and not self.decode:
            raise ValueError("slot_decode=True requires decode=True (it is "
                             "a mode OF the KV-cache path)")
        if self.decode and self.attn_fn is not None:
            # the KV-cache path below always attends with the dense
            # core; silently dropping a mesh-sharded attn_fn (e.g. ring
            # attention) would change sharding semantics without warning
            raise ValueError(
                "decode=True ignores attn_fn: the KV-cache path uses the "
                "dense attention core. Generate with attn_fn=None (the "
                "math is identical for sequence-parallel-trained weights "
                "once gathered), or run a full forward without decode."
            )
        b, t, d = x.shape
        assert d % self.num_heads == 0, "embed dim must divide num_heads"
        # validate window/sinks ONCE, up front: without this the training
        # forward rejects sinks-without-window deep inside
        # dot_product_attention while the decode-cache path silently
        # ignores sinks — the same misconfiguration must fail identically
        # and early on both paths
        if self.sinks < 0:
            raise ValueError(f"sinks must be >= 0, got {self.sinks}")
        if self.sinks and self.window is None:
            raise ValueError(
                f"sinks={self.sinks} requires a sliding window: attention "
                "sinks pin the first keys OUTSIDE the window (StreamingLLM); "
                "without window= every key is attendable and sinks have no "
                "meaning. Pass window=<int> or sinks=0."
            )
        if self.window is not None and self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if (self.kv_block_size > 0) != (self.kv_blocks > 0):
            raise ValueError(
                f"paged KV needs BOTH kv_block_size ({self.kv_block_size}) "
                f"and kv_blocks ({self.kv_blocks}) positive (or both 0 for "
                "the dense layout)")
        if self.kv_block_size and not self.decode:
            raise ValueError(
                "kv_block_size > 0 (paged KV) is a layout OF the decode "
                "cache; build the model with decode=True")
        head_dim = d // self.num_heads
        hkv = self.num_kv_heads or self.num_heads
        if self.num_heads % hkv:
            raise ValueError(
                f"num_heads ({self.num_heads}) must be a multiple of "
                f"num_kv_heads ({hkv})")
        if hkv != self.num_heads:
            # Grouped-query attention: separate projections so K/V carry
            # only hkv heads — the KV cache (and decode HBM traffic)
            # shrinks by num_heads/hkv, and the attention cores consume
            # the grouped layout directly (the Pallas kernel natively,
            # the XLA cores by a fused broadcast).
            q = nn.DenseGeneral(
                (self.num_heads, head_dim), axis=-1, dtype=self.dtype,
                name="q",
            )(x)
            kv = nn.DenseGeneral(
                (2, hkv, head_dim), axis=-1, dtype=self.dtype, name="kv"
            )(x)
            k, v = kv[:, :, 0], kv[:, :, 1]
        else:
            qkv = nn.DenseGeneral(
                (3, self.num_heads, head_dim), axis=-1, dtype=self.dtype,
                name="qkv",
            )(x)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

        if self.decode and self.kv_block_size:
            # ---- paged block-pool KV layout -----------------------------
            # K/V live in a shared pool of fixed-size blocks; each batch
            # row carries a page table of int32 block ids (-1 =
            # unallocated: reads masked, writes dropped).  ONE code path
            # serves any (B, t): the all-slot decode step (B=max_slots,
            # t=1) and batch-1 (chunked) prefill are the same program at
            # different argument shapes — every row advances from its own
            # cursor, writes route through its page-table row, reads
            # gather the row's pages back into a contiguous view.  The
            # indirection is carried as DATA, so page-table churn never
            # retraces a compiled program.
            is_init = not self.has_variable("cache", "cached_k")
            cache_len = (
                t if self.window is None
                else min(self.window + self.sinks + self.ring_slack, t)
            )
            bs_kv = self.kv_block_size
            pages = -(-cache_len // bs_kv)
            r_pad = pages * bs_kv
            quant = self.kv_quant != "none"
            store_dt = _kv_store_dtype(self.kv_quant)
            cached_k = self.variable(
                "cache", "cached_k", jnp.zeros,
                (self.kv_blocks, bs_kv, hkv, head_dim), store_dt or k.dtype,
            )
            cached_v = self.variable(
                "cache", "cached_v", jnp.zeros,
                (self.kv_blocks, bs_kv, hkv, head_dim), store_dt or v.dtype,
            )
            k_scale = v_scale = None
            if quant:
                # per-row-per-head scales, pool-shaped like their rows
                k_scale = self.variable(
                    "cache", "cached_k_scale", jnp.zeros,
                    (self.kv_blocks, bs_kv, hkv), jnp.float32)
                v_scale = self.variable(
                    "cache", "cached_v_scale", jnp.zeros,
                    (self.kv_blocks, bs_kv, hkv), jnp.float32)
            cache_index = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((b,), jnp.int32))
            page_table = self.variable(
                "cache", "page_table",
                lambda: jnp.full((b, pages), -1, jnp.int32))
            # per-row write gate: 0 = parked or mid-prefill, 1 = live.
            # The all-slot decode step rides EVERY row and drifts the
            # cursors of rows it does not own; a mid-prefill row has
            # bound pages (claimed prefix blocks, earlier chunks), so
            # unlike a parked row its drift writes would LAND — into a
            # shared prefix block, or over a windowed ring's in-band
            # keys once the drift outruns the ring slack.  Gating the
            # write on slot_live (the chunk program runs its batch-1
            # view with the gate forced open) makes any decode/prefill
            # interleaving safe; the gate is cache DATA, so flipping it
            # never retraces.
            slot_live = self.variable(
                "cache", "slot_live", lambda: jnp.zeros((b,), jnp.int32))
            slot_pos = None
            valid_len = None
            if self.window is not None:
                slot_pos = self.variable(
                    "cache", "slot_pos",
                    lambda: jnp.full((b, r_pad), -1, jnp.int32))
                # per-row valid-token count for the CURRENT call (see
                # VALID_UNGATED): padded prefill chunks gate their pad
                # positions out of the ring write
                valid_len = self.variable(
                    "cache", "valid_len",
                    lambda: jnp.full((b,), VALID_UNGATED, jnp.int32))
            if not is_init:
                # post-init, t is a CHUNK length (1 for the decode step);
                # the page count is fixed by the stored table, not by t
                pages = page_table.value.shape[1]
                r_pad = pages * bs_kv
                idx = cache_index.value  # [B] per-row cursors
                wpos = idx[:, None] + jnp.arange(t)[None, :]  # [B, T]
                if self.use_rope:
                    q, k = rope(q, wpos), rope(k, wpos)
                pt = page_table.value  # [B, pages]
                rows = jnp.arange(b)[:, None]  # [B, 1]
                live = slot_live.value[:, None] > 0  # [B, 1] write gate
                # the flash-decode kernel serves single-token steps only
                # (chunked prefill is matmul-dense and stays XLA)
                use_kernel = self.attention_impl == "pallas" and t == 1
                if quant:
                    k_store, k_sc = quantize_kv(k, self.kv_quant)
                    v_store, v_sc = quantize_kv(v, self.kv_quant)
                else:
                    k_store, v_store = k, v

                def write(phys, off):
                    cached_k.value = cached_k.value.at[phys, off].set(
                        k_store, mode="drop")
                    cached_v.value = cached_v.value.at[phys, off].set(
                        v_store, mode="drop")
                    if quant:
                        k_scale.value = k_scale.value.at[phys, off].set(
                            k_sc, mode="drop")
                        v_scale.value = v_scale.value.at[phys, off].set(
                            v_sc, mode="drop")

                def gather_view(pool, scale):
                    # -1 ("unallocated") clamps to block 0 purely to
                    # keep the gather in bounds; every such row is
                    # mask-excluded below
                    g = pool[jnp.maximum(pt, 0)]
                    g = g.reshape(b, r_pad, hkv, head_dim)
                    if scale is None:
                        return g
                    s = scale.value[jnp.maximum(pt, 0)].reshape(
                        b, r_pad, hkv)
                    return dequantize_kv(g, s, self.dtype)

                def kernel_out(cursor, sp):
                    from ..ops.pallas_decode import flash_decode_paged

                    return flash_decode_paged(
                        q, cached_k.value, cached_v.value, pt, cursor,
                        slot_pos=sp, window=self.window, sinks=self.sinks,
                        k_scale=k_scale.value if quant else None,
                        v_scale=v_scale.value if quant else None)

                if self.window is None:
                    # logical row == global position.  Write first,
                    # gather after: the chunk's own keys must be in the
                    # attendable view (the dense prefill path's
                    # write-then-read order).
                    keep = (wpos < r_pad) & live  # live rows, in range
                    page = jnp.minimum(wpos // bs_kv, pages - 1)
                    phys = pt[rows, page]
                    phys = jnp.where(keep & (phys >= 0), phys,
                                     self.kv_blocks)
                    off = wpos % bs_kv
                    write(phys, off)
                    if use_kernel:
                        out = kernel_out(wpos[:, 0], None)
                    else:
                        attn_k = gather_view(cached_k.value, k_scale)
                        attn_v = gather_view(cached_v.value, v_scale)
                        allow = (jnp.arange(r_pad)[None, None, :]
                                 <= wpos[:, :, None])  # [B, T, keys]
                        out = dot_product_attention(
                            q, attn_k, attn_v, mask=allow[:, None])
                else:
                    # the logical ring spans ALL paged rows: rounding
                    # cache_len up to a block multiple only RETAINS
                    # more, and retained out-of-band keys are
                    # mask-excluded anyway
                    ring = max(r_pad - self.sinks, 1)
                    # survival window relative to the last REAL position
                    # of this call: per row, one past it is idx + veff
                    # (veff = t when ungated — decode steps, unpadded
                    # prefills — which reduces to the classic
                    # newest-ring-of-the-chunk rule).  Gating on veff
                    # means a padded chunk's pad positions neither write
                    # nor evict, so the ring needs NO slack beyond
                    # sinks + window.
                    veff = jnp.minimum(valid_len.value, t)  # [B]
                    limit = (idx + veff)[:, None]  # [B, 1]
                    keep = (wpos > limit - 1 - ring) & (wpos < limit)
                    if self.sinks:
                        # pinned sinks keep too — but never a pad
                        keep |= (wpos < self.sinks) & (wpos < limit)
                        ring_slot = self.sinks + (wpos - self.sinks) % ring
                        lrow = jnp.where(wpos < self.sinks, wpos, ring_slot)
                    else:
                        lrow = wpos % ring
                    keep &= live  # mid-prefill/parked rows never write
                    phys = pt[rows, lrow // bs_kv]
                    phys = jnp.where(keep & (phys >= 0), phys,
                                     self.kv_blocks)
                    off = lrow % bs_kv
                    if use_kernel:
                        # write-then-attend: at t == 1 the only key the
                        # rolling write can evict sits a full ring
                        # behind the cursor — out of band by
                        # construction (ring >= window) — so the
                        # post-write ring + slot_pos hold exactly the
                        # attendable set, no concat needed
                        write(phys, off)
                        slot_pos.value = slot_pos.value.at[
                            rows, jnp.where(keep, lrow, r_pad)].set(
                            wpos, mode="drop")
                        out = kernel_out(idx, slot_pos.value)
                    else:
                        # read [pages ∥ this chunk] BEFORE the rolling
                        # write — the dense ring's order, so a key this
                        # chunk evicts stays attendable for its own
                        # earlier queries.  Under quantization the
                        # chunk's own keys are attended through their
                        # STORED (dequantized) values so every impl and
                        # the sequential reference see identical math.
                        k_at = (dequantize_kv(k_store, k_sc, self.dtype)
                                if quant else k)
                        v_at = (dequantize_kv(v_store, v_sc, self.dtype)
                                if quant else v)
                        attn_k = jnp.concatenate(
                            [gather_view(cached_k.value, k_scale), k_at],
                            axis=1)
                        attn_v = jnp.concatenate(
                            [gather_view(cached_v.value, v_scale), v_at],
                            axis=1)
                        sp = jnp.concatenate(
                            [slot_pos.value, wpos], axis=1)[:, None, :]
                        qg = wpos[:, :, None]  # [B, T, 1]
                        allow = (sp >= 0) & (sp <= qg)
                        in_band = sp > qg - self.window
                        if self.sinks:
                            in_band |= sp < self.sinks
                        allow &= in_band
                        write(phys, off)
                        slot_pos.value = slot_pos.value.at[
                            rows, jnp.where(keep, lrow, r_pad)].set(
                            wpos, mode="drop")
                        out = dot_product_attention(
                            q, attn_k, attn_v, mask=allow[:, None])
                cache_index.value = idx + t
                return nn.DenseGeneral(
                    d, axis=(-2, -1), dtype=self.dtype, name="out"
                )(out)
            # fall through at init: trace the normal full-length path so
            # every param/cache shape is fixed
        elif self.decode:
            is_init = not self.has_variable("cache", "cached_k")
            # at init, t is the FULL target length -> static cache shape.
            # With a window the cache is `sinks` PINNED slots plus a
            # ROLLING ring of `window` slots (O(sinks + window) memory
            # regardless of generation length); slot positions live in a
            # side buffer so the mask can recover global causality after
            # wraparound.
            cache_len = (
                t if self.window is None
                else min(self.window + self.sinks + self.ring_slack, t)
            )
            quant = self.kv_quant != "none"
            store_dt = _kv_store_dtype(self.kv_quant)
            cached_k = self.variable(
                "cache", "cached_k", jnp.zeros,
                (b, cache_len, hkv, head_dim), store_dt or k.dtype,
            )
            cached_v = self.variable(
                "cache", "cached_v", jnp.zeros,
                (b, cache_len, hkv, head_dim), store_dt or v.dtype,
            )
            k_scale = v_scale = None
            if quant:
                k_scale = self.variable(
                    "cache", "cached_k_scale", jnp.zeros,
                    (b, cache_len, hkv), jnp.float32)
                v_scale = self.variable(
                    "cache", "cached_v_scale", jnp.zeros,
                    (b, cache_len, hkv), jnp.float32)
            # slot mode: one cursor (and one ring position table) PER
            # batch row, so every slot advances independently
            idx_shape = (b,) if self.slot_decode else ()
            cache_index = self.variable(
                "cache", "cache_index", lambda: jnp.zeros(idx_shape, jnp.int32)
            )
            slot_pos = None
            valid_len = None
            if self.window is not None:
                sp_shape = (
                    (b, cache_len) if self.slot_decode else (cache_len,)
                )
                slot_pos = self.variable(
                    "cache", "slot_pos",
                    lambda: jnp.full(sp_shape, -1, jnp.int32),
                )
                # valid-token count for the CURRENT call (VALID_UNGATED
                # = every write real).  Shaped like cache_index; read by
                # the scalar-index prefill path only — slot decode steps
                # one real token per row by construction.
                valid_len = self.variable(
                    "cache", "valid_len",
                    lambda: jnp.full(idx_shape, VALID_UNGATED, jnp.int32),
                )
            if not is_init and self.slot_decode:
                # ONE token per slot, every slot at its own depth.  The
                # math mirrors the scalar-index path exactly (same write
                # layout, same mask algebra) so a slot's token stream is
                # bit-identical to a batch-1 sequential decode.
                if t != 1:
                    raise ValueError(
                        f"slot_decode steps one token per slot (t=1), got "
                        f"t={t}; prefill runs through a batch-1 scalar-index "
                        "model and is spliced into the slot by the engine")
                idx = cache_index.value  # [B] per-slot cursors
                total = cached_k.value.shape[1]
                if self.use_rope:
                    pos = idx[:, None]  # [B, 1] global positions
                    q, k = rope(q, pos), rope(k, pos)
                rows = jnp.arange(b)
                use_kernel = self.attention_impl == "pallas"
                if quant:
                    k_store, k_sc = quantize_kv(k, self.kv_quant)
                    v_store, v_sc = quantize_kv(v, self.kv_quant)
                else:
                    k_store, v_store = k, v

                def write(slot_idx, mode=None):
                    kw = dict(mode=mode) if mode else {}
                    cached_k.value = cached_k.value.at[rows, slot_idx].set(
                        k_store[:, 0], **kw)
                    cached_v.value = cached_v.value.at[rows, slot_idx].set(
                        v_store[:, 0], **kw)
                    if quant:
                        k_scale.value = k_scale.value.at[rows, slot_idx].set(
                            k_sc[:, 0], **kw)
                        v_scale.value = v_scale.value.at[rows, slot_idx].set(
                            v_sc[:, 0], **kw)

                def kernel_out(sp):
                    from ..ops.pallas_decode import flash_decode

                    return flash_decode(
                        q, cached_k.value, cached_v.value, idx,
                        slot_pos=sp, window=self.window, sinks=self.sinks,
                        k_scale=k_scale.value if quant else None,
                        v_scale=v_scale.value if quant else None)

                if self.window is None:
                    # parked slots may have run past the cache end; their
                    # writes drop harmlessly (output is discarded and the
                    # engine resets the cursor on re-admission)
                    write(idx, mode="drop")
                    if use_kernel:
                        out = kernel_out(None)
                        cache_index.value = idx + 1
                        return nn.DenseGeneral(
                            d, axis=(-2, -1), dtype=self.dtype, name="out"
                        )(out)
                    allow = jnp.arange(total)[None, :] <= idx[:, None]
                    attn_k = (dequantize_kv(
                        cached_k.value, k_scale.value, self.dtype)
                        if quant else cached_k.value)
                    attn_v = (dequantize_kv(
                        cached_v.value, v_scale.value, self.dtype)
                        if quant else cached_v.value)
                else:
                    ring = max(total - self.sinks, 1)
                    if self.sinks:
                        ring_slot = self.sinks + (idx - self.sinks) % ring
                        slot = jnp.where(idx < self.sinks, idx, ring_slot)
                    else:
                        slot = idx % ring
                    if use_kernel:
                        # write-then-attend (see the paged branch: the
                        # evicted key is a full ring behind the cursor,
                        # out of band by construction)
                        write(slot)
                        slot_pos.value = slot_pos.value.at[rows, slot].set(
                            idx)
                        out = kernel_out(slot_pos.value)
                        cache_index.value = idx + 1
                        return nn.DenseGeneral(
                            d, axis=(-2, -1), dtype=self.dtype, name="out"
                        )(out)
                    # read [ring ∥ new token] BEFORE the rolling write —
                    # the same order as the scalar path, so the key this
                    # token evicts stays attendable for this very step
                    # (quantized: attend the stored numbers, like every
                    # other read path)
                    ring_k = (dequantize_kv(
                        cached_k.value, k_scale.value, self.dtype)
                        if quant else cached_k.value)
                    ring_v = (dequantize_kv(
                        cached_v.value, v_scale.value, self.dtype)
                        if quant else cached_v.value)
                    k_at = (dequantize_kv(k_store, k_sc, self.dtype)
                            if quant else k)
                    v_at = (dequantize_kv(v_store, v_sc, self.dtype)
                            if quant else v)
                    attn_k = jnp.concatenate([ring_k, k_at], axis=1)
                    attn_v = jnp.concatenate([ring_v, v_at], axis=1)
                    sp = jnp.concatenate(
                        [slot_pos.value, idx[:, None]], axis=1)  # [B, total+1]
                    qg = idx[:, None]
                    allow = (sp >= 0) & (sp <= qg)
                    in_band = sp > qg - self.window
                    if self.sinks:
                        in_band |= sp < self.sinks
                    allow &= in_band
                    write(slot)
                    slot_pos.value = slot_pos.value.at[rows, slot].set(idx)
                cache_index.value = idx + 1
                allow = allow[:, None, None, :]  # [B, 1, 1, keys]
                out = dot_product_attention(q, attn_k, attn_v, mask=allow)
                return nn.DenseGeneral(
                    d, axis=(-2, -1), dtype=self.dtype, name="out"
                )(out)
            if not is_init:
                # t == 1: one sampling step.  t > 1: batched PREFILL — the
                # whole prompt's K/V written in one parallel pass (one
                # matmul-dense forward) instead of t sequential steps.
                idx = cache_index.value
                total = cached_k.value.shape[1]
                if self.use_rope:
                    pos = idx + jnp.arange(t)  # global positions
                    q, k = rope(q, pos), rope(k, pos)
                q_glob = (idx + jnp.arange(t))[:, None]
                use_kernel = self.attention_impl == "pallas" and t == 1
                if quant:
                    k_store, k_sc = quantize_kv(k, self.kv_quant)
                    v_store, v_sc = quantize_kv(v, self.kv_quant)
                else:
                    k_store, v_store = k, v

                def kernel_out(sp):
                    from ..ops.pallas_decode import flash_decode

                    # scalar mode: one shared cursor (and ring position
                    # table) for every batch row — broadcast both into
                    # the kernel's per-slot layout
                    return flash_decode(
                        q, cached_k.value, cached_v.value,
                        jnp.broadcast_to(idx, (b,)).astype(jnp.int32),
                        slot_pos=(None if sp is None else jnp.broadcast_to(
                            sp[None], (b, total))),
                        window=self.window, sinks=self.sinks,
                        k_scale=k_scale.value if quant else None,
                        v_scale=v_scale.value if quant else None)

                if self.window is None:
                    cached_k.value = jax.lax.dynamic_update_slice(
                        cached_k.value, k_store, (0, idx, 0, 0)
                    )
                    cached_v.value = jax.lax.dynamic_update_slice(
                        cached_v.value, v_store, (0, idx, 0, 0)
                    )
                    if quant:
                        k_scale.value = jax.lax.dynamic_update_slice(
                            k_scale.value, k_sc, (0, idx, 0))
                        v_scale.value = jax.lax.dynamic_update_slice(
                            v_scale.value, v_sc, (0, idx, 0))
                    if use_kernel:
                        out = kernel_out(None)
                        cache_index.value = idx + t
                        return nn.DenseGeneral(
                            d, axis=(-2, -1), dtype=self.dtype, name="out"
                        )(out)
                    # query i (global position idx+i) attends keys [0, idx+i]
                    allow = jnp.arange(total)[None, :] <= q_glob
                    attn_k = (dequantize_kv(
                        cached_k.value, k_scale.value, self.dtype)
                        if quant else cached_k.value)
                    attn_v = (dequantize_kv(
                        cached_v.value, v_scale.value, self.dtype)
                        if quant else cached_v.value)
                else:
                    # `total` is the ring length (the STORED cache's
                    # shape — cache_len above is only meaningful at init,
                    # where t is the full target length).  Reads go
                    # against [old ring ∥ this chunk]: a chunked
                    # prefill's EARLY queries need band keys that the
                    # chunk's own newest tokens are about to overwrite,
                    # so the read precedes the rolling write.  Positions
                    # are disjoint (ring < idx ≤ chunk); -1 marks
                    # unwritten slots, never attendable.
                    wpos = idx + jnp.arange(t)
                    # write layout: position p lives at slot p while
                    # p < sinks (pinned, never evicted), else at
                    # sinks + (p - sinks) % ring.  Only sink positions
                    # and the call's newest `ring` REAL tokens survive a
                    # read-back (veff gates padded prefill — see
                    # VALID_UNGATED: pads neither write nor evict, which
                    # is what lets the ring be exactly sinks + window),
                    # so everything else routes to the out-of-range slot
                    # and mode="drop" discards it — this also keeps the
                    # scatter duplicate-free.
                    ring = max(total - self.sinks, 1)
                    veff = jnp.minimum(valid_len.value, t)
                    limit = idx + veff  # one past the last REAL position
                    keep = (wpos > limit - 1 - ring) & (wpos < limit)
                    if self.sinks:
                        # pinned sinks keep too — but never a pad
                        keep |= (wpos < self.sinks) & (wpos < limit)
                        ring_slot = self.sinks + (wpos - self.sinks) % ring
                        slot = jnp.where(wpos < self.sinks, wpos, ring_slot)
                    else:
                        slot = wpos % ring
                    slots = jnp.where(keep, slot, total)  # total = dropped

                    def write():
                        cached_k.value = cached_k.value.at[:, slots].set(
                            k_store, mode="drop")
                        cached_v.value = cached_v.value.at[:, slots].set(
                            v_store, mode="drop")
                        if quant:
                            k_scale.value = k_scale.value.at[:, slots].set(
                                k_sc, mode="drop")
                            v_scale.value = v_scale.value.at[:, slots].set(
                                v_sc, mode="drop")
                        slot_pos.value = slot_pos.value.at[slots].set(
                            wpos, mode="drop")

                    if use_kernel:
                        # write-then-attend: at t == 1 the evicted key is
                        # a full ring behind the cursor — out of band
                        write()
                        out = kernel_out(slot_pos.value)
                        cache_index.value = idx + t
                        return nn.DenseGeneral(
                            d, axis=(-2, -1), dtype=self.dtype, name="out"
                        )(out)
                    k_at = (dequantize_kv(k_store, k_sc, self.dtype)
                            if quant else k)
                    v_at = (dequantize_kv(v_store, v_sc, self.dtype)
                            if quant else v)
                    ring_k = (dequantize_kv(
                        cached_k.value, k_scale.value, self.dtype)
                        if quant else cached_k.value)
                    ring_v = (dequantize_kv(
                        cached_v.value, v_scale.value, self.dtype)
                        if quant else cached_v.value)
                    attn_k = jnp.concatenate([ring_k, k_at], axis=1)
                    attn_v = jnp.concatenate([ring_v, v_at], axis=1)
                    sp = jnp.concatenate([slot_pos.value, wpos])[None, :]
                    allow = (sp >= 0) & (sp <= q_glob)
                    in_band = sp > q_glob - self.window
                    if self.sinks:
                        in_band |= sp < self.sinks
                    allow &= in_band
                    write()
                cache_index.value = idx + t
                allow = allow[None, None]  # [1, 1, t, keys]
                out = dot_product_attention(q, attn_k, attn_v, mask=allow)
                return nn.DenseGeneral(
                    d, axis=(-2, -1), dtype=self.dtype, name="out"
                )(out)
            # fall through at init: trace the normal full-length path so
            # every param/cache shape is fixed

        if self.use_rope:
            pos = jnp.arange(t)
            q, k = rope(q, pos), rope(k, pos)
        attn = (
            self.attn_fn
            if self.attn_fn is not None
            else partial(dot_product_attention, causal=True,
                         window=self.window, sinks=self.sinks)
        )
        # a custom attn_fn owns its own windowing (attention_core(...,
        # window=...) builds one); the model only windows the defaults
        out = attn(q, k, v)  # [B, T, H, Dh]
        return nn.DenseGeneral(d, axis=(-2, -1), dtype=self.dtype, name="out")(out)


class DecoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0
    attn_fn: Optional[AttnFn] = None
    use_rope: bool = True
    decode: bool = False
    num_kv_heads: Optional[int] = None
    window: Optional[int] = None
    sinks: int = 0
    norm: str = "layernorm"
    mlp: str = "gelu"
    norm_eps: float = 1e-6
    slot_decode: bool = False
    ring_slack: int = 0
    kv_block_size: int = 0
    kv_blocks: int = 0
    attention_impl: str = "xla"  # decode core: xla | pallas flash-decode
    kv_quant: str = "none"  # KV-cache storage: none | int8 | fp8

    @nn.compact
    def __call__(self, x, train: bool = True):
        # train is positional-or-keyword (unlike the package's other
        # blocks) so nn.remat can mark it static via static_argnums
        y = _norm_layer(self.norm, self.dtype, eps=self.norm_eps)(x)
        y = CausalSelfAttention(
            self.num_heads, dtype=self.dtype, attn_fn=self.attn_fn,
            use_rope=self.use_rope, decode=self.decode,
            num_kv_heads=self.num_kv_heads, window=self.window,
            sinks=self.sinks, slot_decode=self.slot_decode,
            ring_slack=self.ring_slack, kv_block_size=self.kv_block_size,
            kv_blocks=self.kv_blocks, attention_impl=self.attention_impl,
            kv_quant=self.kv_quant,
        )(y)
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        x = x + y
        y = _norm_layer(self.norm, self.dtype, eps=self.norm_eps)(x)
        d = x.shape[-1]
        if self.mlp == "swiglu":
            # Llama-style gated MLP: gate/up column matmuls fused by XLA,
            # SiLU gating on the VPU, biasless (explicit names keep the
            # TP rules exact: gate/up column-sharded, down row-sharded)
            gate = nn.Dense(self.mlp_dim, dtype=self.dtype, use_bias=False,
                            name="gate")(y)
            up = nn.Dense(self.mlp_dim, dtype=self.dtype, use_bias=False,
                          name="up")(y)
            y = nn.silu(gate) * up
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
            y = nn.Dense(d, dtype=self.dtype, use_bias=False, name="down")(y)
        elif self.mlp == "gelu":
            y = nn.Dense(self.mlp_dim, dtype=self.dtype)(y)
            y = nn.gelu(y, approximate=True)
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
            y = nn.Dense(d, dtype=self.dtype)(y)
        else:
            raise ValueError(f"unknown mlp {self.mlp!r} (gelu|swiglu)")
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        return x + y


class MoEDecoderBlock(nn.Module):
    """DecoderBlock with the MLP replaced by a Switch/GShard MoE layer.

    ``moe_fn`` comes from ``parallel.ep.moe_apply(expert_fn, mesh, ...)``
    with the matching ``expert_fn`` being this block's per-expert MLP
    (``w1/b1/w2/b2`` — see :func:`moe_expert_fn`): experts live sharded
    on the ``expert`` mesh axis, tokens are dispatched by the in-block
    router, and the load-balance auxiliary loss is sown into the
    ``"losses"`` collection (``lm_loss_fn`` adds it, weighted by the
    model's ``moe_aux_weight``).
    """

    num_heads: int
    mlp_dim: int
    num_experts: int
    moe_fn: Callable
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0
    attn_fn: Optional[AttnFn] = None
    use_rope: bool = True
    decode: bool = False
    num_kv_heads: Optional[int] = None
    window: Optional[int] = None
    sinks: int = 0
    norm: str = "layernorm"
    norm_eps: float = 1e-6
    slot_decode: bool = False
    ring_slack: int = 0
    kv_block_size: int = 0
    kv_blocks: int = 0
    attention_impl: str = "xla"  # decode core: xla | pallas flash-decode
    kv_quant: str = "none"  # KV-cache storage: none | int8 | fp8

    @nn.compact
    def __call__(self, x, train: bool = True):
        y = _norm_layer(self.norm, self.dtype, eps=self.norm_eps)(x)
        y = CausalSelfAttention(
            self.num_heads, dtype=self.dtype, attn_fn=self.attn_fn,
            use_rope=self.use_rope, decode=self.decode,
            num_kv_heads=self.num_kv_heads, window=self.window,
            sinks=self.sinks, slot_decode=self.slot_decode,
            ring_slack=self.ring_slack, kv_block_size=self.kv_block_size,
            kv_blocks=self.kv_blocks, attention_impl=self.attention_impl,
            kv_quant=self.kv_quant,
        )(y)
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        x = x + y
        y = _norm_layer(self.norm, self.dtype, eps=self.norm_eps)(x)
        b, t, d = y.shape
        e, m = self.num_experts, self.mlp_dim
        init = nn.initializers.lecun_normal()
        router = self.param("router", init, (d, e), jnp.float32)
        experts = {
            "w1": self.param("w1", init, (e, d, m), jnp.float32),
            "b1": self.param("b1", nn.initializers.zeros, (e, m), jnp.float32),
            "w2": self.param("w2", init, (e, m, d), jnp.float32),
            "b2": self.param("b2", nn.initializers.zeros, (e, d), jnp.float32),
        }
        experts = jax.tree.map(lambda p: jnp.asarray(p, self.dtype), experts)
        toks = y.reshape(b * t, d)
        out, aux = self.moe_fn(experts, jnp.asarray(router, jnp.float32), toks)
        self.sow("losses", "moe_aux", aux)
        out = nn.Dropout(self.dropout, deterministic=not train)(out.reshape(b, t, d))
        return x + out


def moe_expert_fn(p, x):
    """The per-expert MLP matching ``MoEDecoderBlock``'s params — pass to
    ``parallel.ep.moe_apply`` when building the block's ``moe_fn``."""
    return jax.nn.gelu(x @ p["w1"] + p["b1"], approximate=True) @ p["w2"] + p["b2"]


class TransformerLM(nn.Module):
    """Decoder-only LM: tokens [B, T] int32 → logits [B, T, vocab] f32.

    Position t's logits predict token t+1 (standard autoregressive
    convention; ``next_token_loss`` does the shift).  With
    ``tie_embeddings`` the output head reuses the input table
    (logits = h @ E^T).
    """

    vocab: int
    depth: int = 4
    dim: int = 256
    num_heads: int = 4
    mlp_dim: int = 1024
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0
    attn_fn: Optional[AttnFn] = None
    use_rope: bool = True
    tie_embeddings: bool = True
    decode: bool = False
    # continuous-batching decode (serve/engine.py): per-slot cache
    # cursors so independent requests at different depths share ONE
    # compiled single-token step.  Requires decode=True.
    slot_decode: bool = False
    # LEGACY extra windowed-ring capacity (see CausalSelfAttention
    # .ring_slack) — the serving engine no longer needs it: the dynamic
    # valid_len operand gates pad writes out of the exactly-sized ring
    ring_slack: int = 0
    # paged KV cache (serve/engine.py layout="paged"): per-layer K/V in
    # a shared pool of kv_blocks fixed-size blocks, indexed through a
    # per-row page table carried as device data (see
    # CausalSelfAttention.kv_block_size).  0/0 = dense layout.
    kv_block_size: int = 0
    kv_blocks: int = 0
    attention_impl: str = "xla"  # decode core: xla | pallas flash-decode
    kv_quant: str = "none"  # KV-cache storage: none | int8 | fp8
    num_kv_heads: Optional[int] = None  # GQA: grouped KV heads
    window: Optional[int] = None  # sliding-window attention
    sinks: int = 0  # StreamingLLM attention sinks (with window)
    norm: str = "layernorm"  # layernorm | rmsnorm
    norm_eps: float = 1e-6  # 1e-5 for HF GPT-2 weight interop
    mlp: str = "gelu"  # gelu | swiglu (MoE blocks keep their expert MLP)
    # learned-positions (use_rope=False) table length; REQUIRED for
    # decode with use_rope=False (later calls see t=1, but the param
    # shape is fixed at creation)
    max_len: Optional[int] = None
    # rematerialize each block in the backward pass: activations for only
    # ~one block live at a time, trading ~1 extra forward of FLOPs for
    # O(depth)x less activation memory -> longer sequences / bigger
    # batches per chip (jax.checkpoint, the TPU HBM lever)
    remat: bool = False
    # MoE: every ``moe_every``-th block swaps its MLP for a routed expert
    # layer (0 = dense everywhere).  ``moe_fn`` is built by the caller
    # via parallel.ep.moe_apply(models.moe_expert_fn, mesh, ...) so the
    # expert mesh axis stays a caller decision; the router's
    # load-balance aux loss is added by lm_loss_fn with weight
    # ``moe_aux_weight``.
    moe_every: int = 0
    num_experts: int = 0
    moe_fn: Optional[Callable] = None
    moe_aux_weight: float = 0.01

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        embed = nn.Embed(self.vocab, self.dim, dtype=self.dtype, name="embed")
        x = embed(tokens)
        if not self.use_rope:
            t = tokens.shape[-1]
            # the table length must be call-shape-independent once the
            # param exists (flax shape-checks reuse): max_len pins it for
            # decode (where later calls see t=1); default = first-call t
            pos_tab = self.param(
                "pos_embedding", nn.initializers.normal(0.02),
                (self.max_len or t, self.dim),
            )
            if self.decode:
                # KV-cache decoding sees t=1 (or a prompt chunk): take the
                # rows at the CURRENT global positions, tracked by a
                # cursor in the cache — x + pos_tab[None] would silently
                # broadcast the whole table over the short chunk.  Slot
                # mode keeps one cursor per row (each slot is its own
                # request at its own depth).
                pos_index = self.variable(
                    "cache", "pos_index",
                    lambda: jnp.zeros(
                        (tokens.shape[0],) if self.slot_decode else (),
                        jnp.int32),
                )
                if not self.is_initializing():
                    if self.slot_decode:
                        if t != 1 and not self.kv_block_size:
                            raise ValueError(
                                "slot_decode with use_rope=False steps one "
                                f"token per slot (t=1), got t={t}")
                        # each row reads its own t rows of the table from
                        # its cursor (t=1 for the decode step; paged
                        # chunked prefill feeds t=chunk).  The gather
                        # clamps parked slots past the table end — their
                        # output is discarded by the engine anyway
                        pos = (pos_index.value[:, None]
                               + jnp.arange(t)[None, :])  # [B, T]
                        rows = jnp.take(
                            jnp.asarray(pos_tab), pos, axis=0
                        )  # [B, T, dim]
                        pos_index.value = pos_index.value + t
                        x = x + jnp.asarray(rows, self.dtype)
                    else:
                        rows = jax.lax.dynamic_slice(
                            jnp.asarray(pos_tab), (pos_index.value, 0),
                            (t, self.dim),
                        )
                        pos_index.value = pos_index.value + t
                        x = x + jnp.asarray(rows, self.dtype)[None]
                else:
                    x = x + jnp.asarray(pos_tab, self.dtype)[None, :t]
            else:
                x = x + jnp.asarray(pos_tab, self.dtype)[None, :t]
        if self.moe_every:
            # validate up front: a silently-dense "MoE" model (moe_every >
            # depth) or a late per-block error would mask misconfiguration
            if self.moe_fn is None or self.num_experts < 1:
                raise ValueError(
                    "moe_every > 0 needs moe_fn (parallel.ep.moe_apply("
                    "models.moe_expert_fn, mesh, ...)) and num_experts"
                )
            if self.moe_every > self.depth:
                raise ValueError(
                    f"moe_every ({self.moe_every}) > depth ({self.depth}): "
                    "no block would be MoE"
                )
            # decode note: each cache step routes only B tokens (not a
            # mesh multiple) — build the decode moe_fn with
            # pad_tokens=True and an explicit capacity sized for B plus
            # padding headroom (moe_apply enforces both)
        block_cls = maybe_remat(
            DecoderBlock, self.remat and not self.decode, train_argnum=2
        )
        moe_cls = maybe_remat(
            MoEDecoderBlock, self.remat and not self.decode, train_argnum=2
        )
        for i in range(self.depth):
            if self.moe_every and (i + 1) % self.moe_every == 0:
                x = moe_cls(
                    self.num_heads, self.mlp_dim, self.num_experts,
                    self.moe_fn, dtype=self.dtype, dropout=self.dropout,
                    attn_fn=self.attn_fn, use_rope=self.use_rope,
                    decode=self.decode, num_kv_heads=self.num_kv_heads,
                    window=self.window, sinks=self.sinks, norm=self.norm,
                    norm_eps=self.norm_eps, name=f"block{i}",
                    slot_decode=self.slot_decode, ring_slack=self.ring_slack,
                    kv_block_size=self.kv_block_size, kv_blocks=self.kv_blocks,
                    attention_impl=self.attention_impl,
                    kv_quant=self.kv_quant,
                )(x, train)
            else:
                x = block_cls(
                    self.num_heads, self.mlp_dim, dtype=self.dtype,
                    dropout=self.dropout, attn_fn=self.attn_fn,
                    use_rope=self.use_rope, decode=self.decode,
                    num_kv_heads=self.num_kv_heads, window=self.window,
                    sinks=self.sinks, norm=self.norm, mlp=self.mlp,
                    norm_eps=self.norm_eps, name=f"block{i}",
                    slot_decode=self.slot_decode, ring_slack=self.ring_slack,
                    kv_block_size=self.kv_block_size, kv_blocks=self.kv_blocks,
                    attention_impl=self.attention_impl,
                    kv_quant=self.kv_quant,
                )(x, train)
        x = _norm_layer(self.norm, self.dtype, name="final_ln", eps=self.norm_eps)(x)
        if self.tie_embeddings:
            logits = embed.attend(x)  # h @ E^T
        else:
            logits = nn.Dense(self.vocab, dtype=self.dtype, name="head")(x)
        return jnp.asarray(logits, jnp.float32)


def next_token_loss(logits, tokens, mask=None):
    """Mean next-token cross-entropy.

    ``logits`` [B, T, V] (position t predicts token t+1), ``tokens``
    [B, T] int; ``mask`` optional [B, T] (True = count this *target*
    position).  f32 log-softmax regardless of model compute dtype.
    """
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]  # [B, T-1]
    if mask is not None:
        m = mask[:, 1:].astype(nll.dtype)
        return (nll * m).sum() / jnp.maximum(m.sum(), 1)
    return nll.mean()


def lm_loss_fn(model: TransformerLM) -> Callable:
    """Adapt the LM to the framework loss signature
    (``fn(params, model_state, batch, train, rng=None)``) so every
    compiled step maker — DP/FSDP/TP — accepts it unchanged.  The batch
    is ``{"tokens": [B, T]}`` with optional ``{"mask": [B, T]}``."""

    moe = getattr(model, "moe_every", 0) > 0

    def fn(params, model_state, batch, train: bool, rng=None):
        rngs = {"dropout": rng} if (train and rng is not None) else None
        if moe:
            # "losses" holds the sown per-block MoE load-balance terms
            logits, sown = model.apply(
                {"params": params}, batch["tokens"], train=train, rngs=rngs,
                mutable=["losses"],
            )
            aux_terms = jax.tree.leaves(sown.get("losses", {}))
        else:
            logits = model.apply(
                {"params": params}, batch["tokens"], train=train, rngs=rngs
            )
            aux_terms = []
        loss = next_token_loss(logits, batch["tokens"], batch.get("mask"))
        if aux_terms and train:
            loss = loss + model.moe_aux_weight * sum(aux_terms) / len(aux_terms)
        return loss, (model_state, logits)

    return fn


def make_decode_cache(model: TransformerLM, batch: int, total_len: int):
    """Fresh zero KV cache for a ``decode=True`` model, shaped for
    ``batch`` rows out to ``total_len`` tokens.

    Shapes come from an abstract init trace of the FULL length — no
    forward pass, no throwaway parameter materialization.  Shared by
    :func:`generate` (one cache per sampling call) and the continuous-
    batching engine (``serve/engine.py`` — one slot cache plus a batch-1
    prefill template).  Zero-fill is right for K/V and every cursor, but
    the windowed ring's ``slot_pos`` initializer is -1 ("unwritten, never
    attendable") — a zero there would masquerade as a written position-0
    key.
    """
    spec = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0), jnp.zeros((batch, total_len), jnp.int32),
            train=False,
        )
    )["cache"]

    def _cache_leaf(path, s):
        name = getattr(path[-1], "key", None)
        # -1 sentinels: slot_pos ("unwritten, never attendable") and the
        # paged page_table ("unallocated: reads masked, writes dropped")
        if name in ("slot_pos", "page_table"):
            return jnp.full(s.shape, -1, s.dtype)
        # valid_len zero would gate EVERY write out — fresh caches run
        # ungated (decode steps, unpadded prefills); the serving engine
        # arms the gate per padded prefill call
        if name == "valid_len":
            return jnp.full(s.shape, VALID_UNGATED, s.dtype)
        return jnp.zeros(s.shape, s.dtype)

    return jax.tree_util.tree_map_with_path(_cache_leaf, spec)


def generate(
    model: TransformerLM,
    params,
    prompt,
    total_len: int,
    temperature: float = 0.0,
    rng=None,
    top_k: int = 0,
    top_p: float = 1.0,
):
    """Autoregressive sampling with the KV cache, as ONE compiled program.

    ``model`` must be constructed with ``decode=True``.  Learned
    positions (``use_rope=False``, e.g. imported GPT-2) decode through
    the cache's ``pos_index`` cursor and additionally need ``max_len``
    set (and ``total_len <= max_len``).  The prompt [B, P] int32 is
    PREFILLED in one parallel
    full-width forward (writing all P keys/values into the cache at
    once), then a ``lax.scan`` of single-token cache steps samples out
    to ``total_len``: greedy at ``temperature=0``, else softmax
    sampling with ``rng``.  ``top_k`` keeps only the k highest logits
    and ``top_p`` keeps the smallest nucleus with cumulative probability
    >= p (both compose with temperature; 0 / 1.0 disable).  Static
    shapes throughout — one compile per (B, P, total_len).

    Returns tokens [B, total_len] (prompt included).
    """
    if not model.decode:
        raise ValueError("generate() needs a model built with decode=True")
    if model.kv_block_size:
        raise ValueError(
            "generate() decodes through the dense contiguous cache; paged "
            "KV (kv_block_size > 0) is the serving engine's layout — drop "
            "kv_block_size/kv_blocks here, or serve through "
            "serve.LMEngine(layout='paged')")
    if not model.use_rope:
        # learned positions decode via the pos_index cursor — but the
        # table is finite, and dynamic_slice would silently CLAMP past
        # its end (wrong positions, no error); bound it here, host-side
        if model.max_len is None:
            raise ValueError(
                "generate() with use_rope=False needs max_len set on the "
                "model (the learned positional table's length)")
        if total_len > model.max_len:
            raise ValueError(
                f"total_len ({total_len}) exceeds the learned positional "
                f"table (max_len={model.max_len})")
    prompt = jnp.asarray(prompt, jnp.int32)
    bsz, plen = prompt.shape
    if not (0 < plen <= total_len):
        raise ValueError(f"need 0 < prompt len ({plen}) <= total_len ({total_len})")
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 samples stochastically — pass rng "
                         "(a jax.random.PRNGKey) or use temperature=0 for greedy")
    if top_k < 0 or not (0.0 < top_p <= 1.0):
        raise ValueError(f"need top_k >= 0 and 0 < top_p <= 1, got {top_k}, {top_p}")
    if (top_k or top_p < 1.0) and temperature == 0.0:
        raise ValueError("top_k/top_p filter a sampling distribution — "
                         "set temperature > 0 (greedy ignores them)")
    if total_len == plen:
        # score-only: nothing to sample, so skip the prefill forward
        # entirely (its cache and first-token draw would be discarded)
        return prompt
    cache = make_decode_cache(model, bsz, total_len)
    key = rng if rng is not None else jax.random.PRNGKey(0)

    vocab = model.vocab
    k_eff = top_k if 0 < top_k < vocab else 0  # k >= V keeps everything

    def sample(logits, sub):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # filter math in f32: a bf16 cumsum rounds tail probabilities
        # away and saturates below 1.0, silently disabling the nucleus
        # cutoff at realistic vocab sizes (same reason the loss path
        # upcasts its log-softmax)
        logits = logits.astype(jnp.float32) / temperature
        if k_eff or top_p < 1.0:
            # ONE descending sort serves both filters
            sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
            cutoff = jnp.full((logits.shape[0], 1), -jnp.inf, jnp.float32)
            if k_eff:
                cutoff = sorted_logits[:, k_eff - 1 : k_eff]
            if top_p < 1.0:
                # nucleus: keep the smallest prefix (by descending prob)
                # with cumulative probability >= top_p; the first token
                # past the threshold stays in (inclusive convention)
                probs = jax.nn.softmax(sorted_logits, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                keep = cum - probs < top_p
                p_cut = jnp.min(
                    jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
                )
                cutoff = jnp.maximum(cutoff, p_cut)
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
        return jax.random.categorical(sub, logits, axis=-1).astype(jnp.int32)

    # prefill: one parallel pass over the whole prompt
    logits_p, mut = model.apply(
        {"params": params, "cache": cache}, prompt, train=False, mutable=["cache"]
    )
    cache = mut["cache"]
    key, sub = jax.random.split(key)
    first = sample(logits_p[:, -1], sub)

    def step(carry, _):
        cache, tok, key = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            train=False, mutable=["cache"],
        )
        key, sub = jax.random.split(key)
        nxt = sample(logits[:, 0], sub)
        return (mut["cache"], nxt, key), nxt

    (_, _, _), toks = jax.lax.scan(
        step, (cache, first, key), None, length=total_len - plen - 1
    )
    out = jnp.concatenate([prompt, first[:, None], toks.T], axis=1)
    return out


def _validate_pp_boundaries(boundaries, S: int, depth: int, what: str):
    """Planner boundaries sanity: S+1 monotone cut points covering the
    whole stack with >= 1 block per stage.  Returns them as a tuple."""
    b = tuple(int(x) for x in boundaries)
    if len(b) != S + 1:
        raise ValueError(
            f"{what}: boundaries needs S+1 = {S + 1} cut points for the "
            f"{S}-stage pipe axis, got {len(b)} ({list(b)})")
    if b[0] != 0 or b[-1] != depth:
        raise ValueError(
            f"{what}: boundaries must span the whole stack "
            f"(0 .. depth={depth}), got {list(b)}")
    if any(b[s + 1] <= b[s] for s in range(S)):
        raise ValueError(
            f"{what}: every stage needs >= 1 block (strictly increasing "
            f"boundaries), got {list(b)}")
    return b


def _pp_validate_and_stage(model: "TransformerLM", mesh, pipe_axis: str, what: str,
                           blocked: bool = True, boundaries=None):
    """Shared lm_pp/lm_pp_1f1b front half: validate the model is
    pipelineable, and build the per-stage callable.  Returns
    ``(S, V, stage_fn)`` — V logical blocks hosted per pipe device
    (``max(counts)`` under planner ``boundaries``, whose non-uniform
    splits ride a counts-aware ``chunk_stages``).  ``blocked=True``
    wraps V > 1 into one ``chunk_stages`` scan per tick (GPipe / plain
    1F1B); ``blocked=False`` returns the single-block callable for the
    interleaved 1F1B schedule, which applies one logical block per tick
    itself."""
    from ..parallel.pp import chunk_stages

    if not model.use_rope:
        raise ValueError(f"{what} needs use_rope=True (a positional table "
                         "would have to enter mid-pipeline)")
    if model.dropout:
        raise ValueError(f"{what} supports dropout=0 only (no rng stream "
                         "threads through the pipeline schedule)")
    if model.moe_every:
        raise ValueError(
            f"{what} does not support moe_every > 0: MoE and dense blocks "
            "have different param trees, so blocks cannot stack as "
            "homogeneous pipe stages"
        )
    S = mesh.shape[pipe_axis]
    if boundaries is not None:
        if not blocked:
            raise ValueError(
                f"{what}: planner boundaries use the blocked chunk "
                "layout and cannot combine with interleave=True (the "
                "round-robin placement has no contiguous stage ranges)")
        boundaries = _validate_pp_boundaries(boundaries, S, model.depth, what)
        counts = [boundaries[s + 1] - boundaries[s] for s in range(S)]
        V = max(counts)
    else:
        if model.depth % S:
            raise ValueError(
                f"model.depth ({model.depth}) must be a multiple of the "
                f"'{pipe_axis}' axis size ({S}) — or pass a pp plan, "
                "whose non-uniform boundaries lift the divisibility "
                "requirement"
            )
        V = model.depth // S
        counts = None

    blk = DecoderBlock(
        model.num_heads, model.mlp_dim, dtype=model.dtype,
        dropout=0.0, use_rope=model.use_rope, attn_fn=model.attn_fn,
        num_kv_heads=model.num_kv_heads, window=model.window,
        sinks=model.sinks, norm=model.norm, mlp=model.mlp,
        norm_eps=model.norm_eps,
    )

    def base_fn(p, x):
        return blk.apply({"params": p}, x, train=False)

    if counts is not None and V > 1 and any(c != V for c in counts):
        # non-uniform planner split: idle pad chunks cond-skipped per
        # device off the static counts table
        return S, V, chunk_stages(base_fn, counts=counts, axis=pipe_axis)
    return S, V, (base_fn if V == 1 or not blocked else chunk_stages(base_fn))


def _pp_split_params(model: "TransformerLM", mesh, pipe_axis: str, S: int, V: int,
                     placement: str = "blocked", boundaries=None):
    """Shared splitter: full param tree -> ``{"outer", "stages"}`` with
    block trees stacked (``(S, V, ...)`` when V > 1) on a leading dim
    sharded over ``pipe_axis``.

    ``placement`` fixes which logical block lands at ``[device, chunk]``:
    ``"blocked"`` (device s hosts consecutive blocks ``s·V … s·V+V-1`` —
    the ``chunk_stages`` layout both GPipe and plain 1F1B scan over) or
    ``"interleaved"`` (device i's chunk c hosts block ``c·S + i`` — the
    round-robin layout ``pipeline_grads_1f1b(interleave=V)`` schedules).
    Within one placement the two schedules share the tree, so their
    checkpoints/shardings are interchangeable.

    Planner ``boundaries`` replace the uniform blocked grouping with
    the plan's contiguous ranges; devices hosting fewer than
    ``V = max(counts)`` blocks are padded with zero-param chunks the
    counts-aware ``chunk_stages`` never executes (zero grads in, zero
    updates out — the optimizer cannot move them)."""
    from ..parallel.pp import stack_stage_params

    def split_params(params):
        stages = [params[f"block{i}"] for i in range(model.depth)]
        outer = {k: v for k, v in params.items() if not k.startswith("block")}
        if boundaries is not None:
            groups = [list(stages[boundaries[s]:boundaries[s + 1]])
                      for s in range(S)]
            if V > 1:
                pad = jax.tree.map(jnp.zeros_like, stages[0])
                groups = [g + [pad] * (V - len(g)) for g in groups]
                stages = [jax.tree.map(lambda *xs: jnp.stack(xs), *g)
                          for g in groups]
            else:
                stages = [g[0] for g in groups]
        elif V > 1:
            if placement == "interleaved":
                groups = [[stages[c * S + s] for c in range(V)] for s in range(S)]
            else:
                groups = [stages[s * V : (s + 1) * V] for s in range(S)]
            stages = [
                jax.tree.map(lambda *xs: jnp.stack(xs), *g) for g in groups
            ]
        return {
            "outer": outer,
            "stages": stack_stage_params(stages, mesh, pipe_axis),
        }

    return split_params


def _pp_state_shardings(mesh, pipe_axis: str):
    """Shared TrainState sharding builder for the split tree — the
    single implementation lives with the schedule that compiles against
    it (``parallel.pp_1f1b.split_state_shardings``)."""
    from ..parallel.pp_1f1b import split_state_shardings

    return split_state_shardings(mesh, pipe_axis)


def lm_pp(
    model: TransformerLM,
    mesh,
    pipe_axis: str = PIPE_AXIS,
    batch_axis: Optional[str] = None,
    num_microbatches: Optional[int] = None,
    remat: bool = False,
    boundaries=None,
):
    """Pipeline-parallelize the LM: blocks ride the GPipe schedule.

    The decoder stack is the textbook pipeline body — every
    ``DecoderBlock`` preserves the residual-stream shape, so block *i*
    becomes pipe stage *i* (``parallel.pp.pipeline_apply``); the
    embedding lookup, final LayerNorm, and (tied) logits projection
    compose outside the pipelined middle, replicated.

    Returns ``(split_params, loss_fn, state_shardings)``:

    * ``split_params(params)`` maps a full-model param tree to
      ``{"outer": ..., "stages": ...}`` with the S block trees stacked
      on a leading dim sharded over ``pipe_axis``;
    * ``loss_fn`` follows the framework loss signature on the split
      tree (so ``dp.make_train_step`` compiles it unchanged);
    * ``state_shardings(state)`` builds the ``TrainState`` sharding tree
      (outer replicated, stages pipe-sharded, optimizer state
      following its param) to pass as ``state_shardings=``.

    ``batch_axis`` composes data parallelism on a ``(data, pipe)`` mesh.
    Constraints: ``use_rope`` (positions live inside the blocks) and
    ``dropout == 0`` (no rng stream threads through the pipeline ticks).
    ``boundaries`` (a planner's S+1 cut points, ``parallel/pp_plan.py``)
    replaces the uniform block split with the plan's non-uniform stage
    ranges — and lifts the ``depth % S == 0`` requirement.
    """
    from ..parallel.pp import pipeline_apply

    S, V, stage_fn = _pp_validate_and_stage(
        model, mesh, pipe_axis, "lm_pp", boundaries=boundaries)
    fwd = pipeline_apply(
        stage_fn, mesh, axis=pipe_axis, num_microbatches=num_microbatches,
        batch_axis=batch_axis, remat=remat,
    )
    embed = nn.Embed(model.vocab, model.dim, dtype=model.dtype)
    ln = _norm_layer(model.norm, model.dtype, eps=model.norm_eps)
    split_params = _pp_split_params(
        model, mesh, pipe_axis, S, V, boundaries=boundaries)

    def loss_fn(params, model_state, batch, train: bool, rng=None):
        tokens = batch["tokens"]
        outer = params["outer"]
        x = embed.apply({"params": outer["embed"]}, tokens)
        x = fwd(params["stages"], x)
        x = ln.apply({"params": outer["final_ln"]}, x)
        if model.tie_embeddings:
            logits = embed.apply({"params": outer["embed"]}, x, method="attend")
        else:
            logits = nn.Dense(model.vocab, dtype=model.dtype).apply(
                {"params": outer["head"]}, x
            )
        logits = jnp.asarray(logits, jnp.float32)
        return next_token_loss(logits, tokens, batch.get("mask")), (
            model_state, logits,
        )

    return split_params, loss_fn, _pp_state_shardings(mesh, pipe_axis)


class LMPipelineWiring(NamedTuple):
    """Everything ``parallel.pp_1f1b.make_train_step_1f1b`` needs, with
    the interleave factor attached so callers never recompute
    ``depth // S`` by hand (``interleave`` is 1 for blocked placement,
    where the V surplus blocks ride inside ``chunk_stages``)::

        w = lm_pp_1f1b(model, mesh, interleave=True)
        step = make_train_step_1f1b(*w.fns, opt, mesh,
                                    interleave=w.interleave, ...)(state)
        state = TrainState.create(w.split_params(params), opt)
    """

    split_params: Callable
    fns: tuple  # (stage_fn, embed_fn, head_fn)
    state_shardings: Callable
    interleave: int = 1


def lm_pp_1f1b(
    model: TransformerLM,
    mesh,
    pipe_axis: str = PIPE_AXIS,
    interleave: bool = False,
    boundaries=None,
):
    """Pipeline-parallelize the LM on the hand-scheduled 1F1B schedule
    (``parallel.pp_1f1b``) instead of GPipe-via-AD (``lm_pp``).

    Same stage decomposition and the SAME ``split_params`` tree as
    ``lm_pp`` — checkpoints and shardings are interchangeable between
    the two schedules — but activation memory is O(S) ring slots per
    device instead of O(M·ticks) scan residuals, so the microbatch
    count (and with it the bubble (S-1)/(M+S-1)) can grow freely.

    ``interleave=True`` switches the V = depth/S surplus blocks from the
    blocked ``chunk_stages`` layout to the Megatron interleaved
    placement (device i hosts blocks ``c·S + i``): the fill/drain
    bubble shrinks ~V-fold.  NOTE the param layouts differ (round-robin
    vs consecutive), so blocked and interleaved split trees are NOT
    interchangeable.

    Because 1F1B interleaves forwards and backwards, the embedding and
    the final-norm/logits/loss run INSIDE the schedule, per microbatch,
    on pipe devices 0 and S-1; their ("outer") grads are psum'd across
    the pipe axis, which also makes tied embeddings sum correctly.

    Returns an ``LMPipelineWiring`` — feed ``w.fns`` and
    ``w.interleave`` to ``parallel.pp_1f1b.make_train_step_1f1b``
    (``num_microbatches`` and ``batch_axis`` also go THERE: they
    parameterize the schedule, not the stage decomposition).
    Constraints are ``lm_pp``'s (rope, no dropout, no MoE) plus: no
    ``batch["mask"]`` support (the per-microbatch loss reads tokens
    only).  ``boundaries`` (planner cut points) selects a non-uniform
    blocked split exactly as in ``lm_pp`` — the two schedules keep
    sharing one split tree — and cannot combine with ``interleave``.
    """
    S, V, stage_fn = _pp_validate_and_stage(
        model, mesh, pipe_axis, "lm_pp_1f1b", blocked=not interleave,
        boundaries=boundaries)
    embed = nn.Embed(model.vocab, model.dim, dtype=model.dtype)
    ln = _norm_layer(model.norm, model.dtype, eps=model.norm_eps)

    def embed_fn(outer, tokens_mb):
        return embed.apply({"params": outer["embed"]}, tokens_mb)

    def head_fn(outer, y, tokens_mb):
        x = ln.apply({"params": outer["final_ln"]}, y)
        if model.tie_embeddings:
            logits = embed.apply({"params": outer["embed"]}, x, method="attend")
        else:
            logits = nn.Dense(model.vocab, dtype=model.dtype).apply(
                {"params": outer["head"]}, x
            )
        return next_token_loss(jnp.asarray(logits, jnp.float32), tokens_mb)

    return LMPipelineWiring(
        _pp_split_params(model, mesh, pipe_axis, S, V,
                         placement="interleaved" if interleave else "blocked",
                         boundaries=boundaries),
        (stage_fn, embed_fn, head_fn),
        _pp_state_shardings(mesh, pipe_axis),
        V if interleave else 1,
    )


def lm_moe_specs(params, axis: str = EXPERT_AXIS):
    """PartitionSpec tree for an MoE LM's params: expert-stacked leaves
    (``w1/b1/w2/b2`` inside MoE blocks, leading dim E) sharded over
    ``axis``; routers and every dense leaf replicated.  Feed through
    ``parallel.tp.state_specs`` + ``sharding.make_shardings`` to get the
    ``state_shardings=`` for ``make_train_step``."""
    from jax.sharding import PartitionSpec as P

    def f(kp, leaf):
        names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in kp]
        if len(names) >= 2 and names[-1] in ("w1", "b1", "w2", "b2"):
            return P(axis, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(f, params)


def lm_tiny(vocab: int = 256, **kw) -> TransformerLM:
    """Test/CI scale: 4 layers, d=128."""
    kw = {"depth": 4, "dim": 128, "num_heads": 4, "mlp_dim": 512, **kw}
    return TransformerLM(vocab=vocab, **kw)


def lm_small(vocab: int = 32000, **kw) -> TransformerLM:
    """GPT-2-small scale: 12 layers, d=768 (~124M with a 32k vocab)."""
    kw = {"depth": 12, "dim": 768, "num_heads": 12, "mlp_dim": 3072, **kw}
    return TransformerLM(vocab=vocab, **kw)


def lm_medium(vocab: int = 32000, **kw) -> TransformerLM:
    """GPT-2-medium scale: 24 layers, d=1024."""
    kw = {"depth": 24, "dim": 1024, "num_heads": 16, "mlp_dim": 4096, **kw}
    return TransformerLM(vocab=vocab, **kw)
