#!/usr/bin/env python
"""fdtpu-lint CLI — the repo's JAX-hazard static-analysis gate.

    # full suite (AST rules + jaxpr-layer variant checks), baseline-aware:
    python bin/lint.py --check

    # CI invocation (fails on any finding not in the checked-in baseline):
    python bin/lint.py --check --baseline fluxdistributed_tpu/analysis/baseline.json

    # lint specific files/dirs (AST layer only):
    python bin/lint.py tests/fixtures_analysis/fdt101_pos.py

    # accept the current findings as the new allowlist:
    python bin/lint.py --update-baseline

Exit codes: 0 = clean (or informational run), 1 = new findings under
``--check`` (each printed as ``file:line: severity [RULE] message``),
2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _bootstrap() -> None:
    """Make the package importable when run as ``python bin/lint.py``
    from a checkout (no install, no PYTHONPATH)."""
    try:
        import fluxdistributed_tpu  # noqa: F401
    except ImportError:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="*",
                   help="files/dirs to AST-scan (default: the package, "
                        "bin/ and bench.py; passing explicit paths skips "
                        "the jaxpr layer unless --jaxpr)")
    p.add_argument("--check", action="store_true",
                   help="fail (exit 1) on findings not in the baseline")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON path (default: "
                        "fluxdistributed_tpu/analysis/baseline.json)")
    p.add_argument("--update-baseline", action="store_true",
                   help="write the current findings to the baseline and exit")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings + summary as one JSON object")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr layer (AST + concurrency rules "
                        "only — no jax import, seconds)")
    p.add_argument("--no-concurrency", action="store_true",
                   help="skip the FDT3xx concurrency layer (lock "
                        "coverage / lock order / thread lifecycle — "
                        "stdlib-ast, on by default even for explicit "
                        "paths)")
    p.add_argument("--jaxpr", action="store_true",
                   help="run the jaxpr layer even when explicit paths "
                        "are given")
    p.add_argument("--variants", default=None,
                   help="comma-separated jaxpr variants to check "
                        "(default: all registered — see "
                        "analysis.variants.variant_names())")
    p.add_argument("--execute", action="store_true",
                   help="also run one real step per variant under "
                        "jax.transfer_guard('disallow') (compiles; "
                        "default only for the variants marked cheap)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _bootstrap()
    from fluxdistributed_tpu import analysis

    baseline_path = args.baseline or analysis.default_baseline_path()
    if args.baseline and not os.path.exists(baseline_path):
        # a mistyped --baseline must not silently become "empty
        # allowlist, everything is new"
        alt = os.path.join(analysis.repo_root(), args.baseline)
        if os.path.exists(alt):
            baseline_path = alt
        elif args.check:
            print(f"lint: baseline {args.baseline} not found", file=sys.stderr)
            return 2

    findings = (analysis.scan_paths(args.paths) if args.paths
                else analysis.scan_repo())

    run_conc = not args.no_concurrency
    if run_conc:
        findings += analysis.run_concurrency_checks(args.paths or None)

    run_jaxpr = (args.jaxpr or not args.paths) and not args.no_jaxpr
    if run_jaxpr:
        # the 8-virtual-device mesh must be pinned before jax touches a
        # backend; force_host_devices also wins over an env-pinned platform
        from fluxdistributed_tpu.mesh import force_host_devices

        force_host_devices(8)
        from fluxdistributed_tpu.analysis import jaxpr_checks

        names = args.variants.split(",") if args.variants else None
        findings += jaxpr_checks.run_jaxpr_checks(
            names=names, execute=True if args.execute else None)

    findings.sort(key=lambda f: (f.file, f.line, f.rule))

    if args.update_baseline:
        # a partial-scope run (explicit paths / --no-jaxpr /
        # --no-concurrency) must not erase allowlist entries it could
        # not have re-observed: keep AST and concurrency entries for
        # unscanned files (or whenever their layer did not run), and
        # jaxpr-layer (FDT2xx) entries whenever the jaxpr layer did
        # not run
        scanned = set(analysis.scanned_files(args.paths or None))

        def _keep(e: dict) -> bool:
            rule = e.get("rule", "")
            if rule.startswith("FDT2"):
                return not run_jaxpr
            if rule.startswith("FDT3"):
                return not run_conc or e.get("file") not in scanned
            return e.get("file") not in scanned

        keep = [e for e in analysis.load_baseline(baseline_path)
                if _keep(e)]
        analysis.save_baseline(baseline_path, findings, keep=keep)
        print(f"lint: wrote {len(findings)} finding(s) + {len(keep)} "
              f"kept out-of-scope entr(ies) to {baseline_path}")
        return 0

    baseline = analysis.load_baseline(baseline_path)
    new, stale = analysis.diff_findings(findings, baseline)
    summary = analysis.summarize(findings, new)
    summary["baseline"] = len(baseline)
    summary["stale_baseline_entries"] = len(stale)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "new": [f.to_dict() for f in new],
            "summary": summary,
        }, indent=2))
    else:
        report = new if args.check else findings
        for f in report:
            print(analysis.format_finding(f))
        for e in stale:
            print(f"note: stale baseline entry {e.get('rule')} "
                  f"{e.get('file')} ({e.get('detail')}) — finding no "
                  "longer fires; shrink the baseline")
        kinds = ", ".join(f"{k}={v}" for k, v in summary["by_rule"].items())
        print(f"lint: {summary['findings']} finding(s) "
              f"({kinds or 'none'}), {len(new)} new vs baseline "
              f"({len(baseline)} entries)")

    if args.check and new:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
