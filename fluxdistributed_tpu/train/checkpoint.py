"""Checkpoint save / load / resume.

The reference is save-only: ``BSON.@save`` of the CPU model every 20
cycles per worker (src/sync.jl:156-161), no optimizer state on disk and
no resume path (SURVEY §5).  This module closes that gap TPU-natively:

* ``save_checkpoint`` — orbax-backed save of the FULL ``TrainState``
  (params + optimizer state + mutable model state + step), written
  per-step under ``<dir>/step_<n>`` like the reference's
  ``weights/$(p)/resnet_50_cycle_$(n)...`` layout.  Writes are
  ATOMIC: orbax streams into a ``step_<n>.tmp.<pid>`` staging dir
  which is renamed into place only once fully on disk, so a ``kill
  -9`` (or a preemption) mid-write can never leave ``latest_step``
  pointing at a half-written checkpoint — the previous one stays
  loadable (docs/robustness.md);
* ``load_checkpoint`` — restore onto host or onto a mesh (replicated),
  defaulting to the latest step — the resume path the reference lacks;
* ``load_checkpoint_elastic`` — restore a checkpoint saved on a
  DIFFERENT topology: leaves round-trip through host arrays and are
  re-committed to the restoring task's shardings, with ZeRO-1's padded
  per-leaf flat shards re-split for the new device count;
* ``latest_step`` — scan a checkpoint dir;
* ``write_resume_manifest`` / ``read_resume_manifest`` — the RESUME
  manifest a preempted run leaves next to its checkpoint (step,
  data-loader cursor, rng derivation note, mesh topology, and — for
  guarded runs — the ``quarantined_items`` the anomaly guard decided
  to skip, see ``train/guard.py``) so the next process can continue
  step-for-step identically, re-skipping the same batches.

Orbax handles sharded arrays natively, so the same call works on a
multi-host pod slice (each host writes its addressable shards).
Checkpoint I/O is wrapped in :func:`fluxdistributed_tpu.faults.
with_retries` (single-process runs), so a transient filesystem hiccup
costs a backoff instead of the run.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from .. import faults
from .. import tree as tree_lib

Pytree = Any

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "load_checkpoint_elastic",
    "latest_step",
    "wait_for_pending",
    "RESUME_MANIFEST",
    "write_resume_manifest",
    "read_resume_manifest",
    "clear_resume_manifest",
]

_STEP_RE = re.compile(r"^step_(\d+)$")

#: filename of the preemption manifest inside a checkpoint directory
RESUME_MANIFEST = "RESUME.json"

# (checkpointer, commit) pairs with an async write still in flight
# (block=False saves); commit publishes the staging dir once finished.
# At most one at a time: save_checkpoint drains it before starting the
# next, and train()/callers drain at exit via wait_for_pending().  The
# expected owner is a single train loop per process; the locks make a
# stray second caller (e.g. an eval thread saving best-so-far)
# serialize instead of corrupting the drain: _PENDING_LOCK protects the
# list, _SAVE_LOCK spans a whole save (drain → write → append) so two
# concurrent saves cannot both observe an empty pending list and race
# their rmtree/write phases.
_PENDING: list = []
_PENDING_LOCK = threading.Lock()
_SAVE_LOCK = threading.Lock()


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"step_{step}")


def wait_for_pending() -> None:
    """Block until any in-flight async save has committed to disk —
    including the atomic staging-dir → ``step_<n>`` rename, which only
    happens once the write is fully finished.

    Single-threaded savers assumed (one train loop per process).  The
    pending reference is removed only after a successful wait+commit,
    so a failed wait leaves it in place and a retry can still await
    the write.
    """
    with _PENDING_LOCK:
        while _PENDING:
            ckptr, commit = _PENDING[-1]
            # a failed WAIT leaves the entry (a retry can still await
            # the write); a failed COMMIT drops it — its staging dir is
            # gone, so re-running the same commit could only raise
            # forever and wedge every subsequent save
            ckptr.wait_until_finished()
            try:
                commit()
            finally:
                _PENDING.pop()


def _barrier(tag: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _commit_rename(tmp: str, final: str) -> None:
    """The atomic-publish rename, isolated so the kill -9 atomicity
    test can interpose on exactly this boundary."""
    os.rename(tmp, final)


def _commit(tmp: str, final: str, overwrite: bool) -> None:
    """Publish a fully-written staging dir as ``step_<n>``.

    The rename runs on the coordinator behind barriers.  The previous
    content of ``final`` (a same-step re-save) is moved aside BEFORE the
    publish rename and deleted after, so at every instant either the old
    or the new complete checkpoint exists under a committed name — never
    a partial one.  Other steps' directories are never touched.

    The overwrite=False refusal is decided on EVERY host (same shared
    checkpoint filesystem, same answer) and raised on every host AFTER
    the final barrier — a coordinator-only raise between the barriers
    would strand the other hosts in ``ckpt_commit`` forever.
    """
    import shutil

    _barrier("ckpt_written")
    refused = not overwrite and os.path.exists(final)
    if jax.process_index() == 0:
        if refused:
            shutil.rmtree(tmp, ignore_errors=True)
        else:
            trash = None
            if os.path.exists(final):
                trash = f"{final}.old.{os.getpid()}"
                os.rename(final, trash)
            _commit_rename(tmp, final)
            if trash is not None:
                shutil.rmtree(trash, ignore_errors=True)
    _barrier("ckpt_commit")
    if refused:
        raise FileExistsError(
            f"checkpoint {final} exists and overwrite=False")


def save_checkpoint(
    state: Pytree, directory: str, step: int, overwrite: bool = True,
    block: bool = True,
) -> str:
    """Write ``state`` (any pytree, e.g. ``TrainState``) at ``directory/step_<n>``.

    Write-then-rename: orbax streams into ``step_<n>.tmp.<pid>`` and the
    staging dir is renamed to ``step_<n>`` only once fully written — a
    process killed mid-write (kill -9, an expired grant window) leaves
    only staging garbage behind, never a half-checkpoint that
    :func:`latest_step` would resume from.  Stale staging dirs from a
    previous dead process are swept on the next save.

    ``block=False`` makes the disk write asynchronous: orbax's save copies
    device arrays to host synchronously (so later donation/mutation of the
    state cannot corrupt the snapshot) and streams to disk in a background
    thread — the train loop keeps stepping during the write, and the
    publish rename happens at the next :func:`wait_for_pending` (train()
    drains before exit).

    Multi-host: the orbax save itself is collective (every host writes its
    addressable shards); the publish rename runs on the coordinator only,
    behind barriers.  Transient I/O failures are retried
    (:func:`..faults.with_retries`) on single-process runs — a multi-host
    retry cannot be coordinated one-sidedly.
    """
    import shutil

    with _SAVE_LOCK:  # one save (drain → write → commit/append) at a time
        wait_for_pending()
        final = _step_dir(directory, step)
        # pid-FREE staging name: the orbax save below is COLLECTIVE, so
        # every host of a multi-host run must aim at the same directory
        # (a per-pid name would scatter shards across one dir per host).
        # Unowned staging dirs are impossible here — the pending list
        # was just drained and _SAVE_LOCK serializes savers — so any
        # pre-existing one is garbage the sweep below removes.
        tmp = f"{final}.tmp.stage"
        ckptr = ocp.StandardCheckpointer()
        if jax.process_index() == 0:
            os.makedirs(os.path.abspath(directory), exist_ok=True)
            # sweep staging garbage: ours from a retry, or a dead
            # predecessor's (an unowned staging dir can never be
            # committed — the pending list above was just drained)
            for name in os.listdir(os.path.abspath(directory)):
                for marker in (".tmp.", ".old."):
                    stem, sep, _ = name.partition(marker)
                    if sep and _STEP_RE.match(stem):
                        shutil.rmtree(
                            os.path.join(os.path.abspath(directory), name),
                            ignore_errors=True)
                        break
        _barrier("ckpt_stage")

        def write():
            faults.fire("checkpoint_save")
            if os.path.exists(tmp):  # partial write from a failed attempt
                shutil.rmtree(tmp, ignore_errors=True)
            ckptr.save(tmp, state)

        if jax.process_count() == 1:
            faults.with_retries(
                write, tries=3, backoff=0.2, site="checkpoint_save",
                retryable=lambda e: isinstance(e, (OSError, IOError)))
        else:
            write()
        if block:
            ckptr.wait_until_finished()
            _commit(tmp, final, overwrite)
        else:
            with _PENDING_LOCK:
                _PENDING.append((ckptr, lambda: _commit(tmp, final, overwrite)))
    return final


def latest_step(directory: str) -> Optional[int]:
    """Largest ``step_<n>`` present in ``directory``, or None."""
    if not os.path.isdir(directory):
        return None
    steps = [
        int(m.group(1))
        for name in os.listdir(directory)
        if (m := _STEP_RE.match(name))
    ]
    return max(steps) if steps else None


def load_checkpoint(
    directory: str,
    target: Optional[Pytree] = None,
    step: Optional[int] = None,
    mesh=None,
) -> Pytree:
    """Restore a checkpoint onto the structure of ``target``.

    ``target=None`` restores the raw pytree as saved (nested dicts of
    host arrays) with no structure requirements — useful when the saving
    optimizer is unknown (e.g. inference tools that only need
    ``restored["params"]``).  ``step=None`` picks the latest (resume
    semantics).  With ``mesh`` given, restored arrays are placed on the
    mesh ready to hand back to a compiled train step: each leaf takes its
    ``target`` leaf's sharding when the target is device-placed (so an
    FSDP-sharded state — or a ZeRO-1 state's flat data-sharded optimizer
    leaves — restores sharded, not gathered), else replicated.
    Restore is topology-independent either way — the placement comes from
    the *restoring* target/mesh, never from the saved run's devices.
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _step_dir(directory, step)
    ckptr = ocp.StandardCheckpointer()

    def _read(fn):
        """Transient-I/O retry boundary for the orbax reads (single
        process only — a multi-host retry cannot be coordinated
        one-sidedly)."""
        def attempt():
            faults.fire("checkpoint_load")
            return fn()

        if jax.process_count() > 1:
            return attempt()
        return faults.with_retries(
            attempt, tries=3, backoff=0.2, site="checkpoint_load",
            retryable=lambda e: isinstance(e, (OSError, IOError)))
    if target is None:
        # Build a host-numpy target from the saved metadata instead of
        # restoring blind: a blind restore re-applies the SAVED device
        # shardings, which fails when the saving topology (e.g. 8 CPU
        # devices) differs from the restoring one (e.g. 1 TPU).
        meta = ckptr.metadata(path)
        # newer orbax wraps the metadata pytree (CompositeCheckpointMetadata
        # .item_metadata.tree); older releases return the tree itself
        item = getattr(meta, "item_metadata", None)
        meta = item.tree if item is not None and hasattr(item, "tree") else meta
        target = jax.tree.map(
            lambda m: np.zeros(m.shape, m.dtype) if hasattr(m, "shape") else m,
            meta,
        )
        restored = _read(lambda: ckptr.restore(path, target=target))
        if mesh is not None:
            from ..sharding import replicate

            restored = replicate(restored, mesh)
        return restored

    if mesh is not None:
        # Restore straight into device-sharded arrays via an ABSTRACT
        # target carrying each target leaf's sharding (its own when
        # device-placed — so FSDP/TP state restores sharded — else
        # replicated).  No host round-trip: to_host on a sharded state
        # would both re-materialize the full model per host (undoing the
        # FSDP memory bound at resume time) and crash outright on
        # multi-host leaves that span non-addressable devices.
        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(mesh, PartitionSpec())

        def abstract(t):
            if hasattr(t, "shape") and hasattr(t, "dtype"):
                sh = getattr(t, "sharding", None)
                sh = sh if isinstance(sh, NamedSharding) else repl
                return jax.ShapeDtypeStruct(np.shape(t), t.dtype, sharding=sh)
            return t

        return _read(
            lambda: ckptr.restore(path, target=jax.tree.map(abstract, target)))

    return _read(lambda: ckptr.restore(
        path, target=jax.tree.map(np.asarray, tree_lib.to_host(target))
    ))


# ---------------------------------------------------------------------------
# elastic restore (device-count change between save and resume)
# ---------------------------------------------------------------------------


def _path_key(entry) -> str:
    """Normalize one jax key-path entry to a plain string so a saved
    nested-dict tree (orbax metadata: everything string-keyed) and a
    live ``TrainState`` (attr/dict/tuple keys) address leaves
    identically."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _leaves_by_path(tree) -> dict:
    from jax.tree_util import tree_flatten_with_path

    flat, _ = tree_flatten_with_path(tree)
    return {tuple(_path_key(k) for k in path): leaf for path, leaf in flat}


def load_checkpoint_elastic(
    directory: str, target: Pytree, step: Optional[int] = None
) -> Pytree:
    """Restore a checkpoint onto ``target`` when the saving topology
    differs from the restoring one (preemption returned a different
    device count — the elastic-resume path, ROADMAP Open item 5).

    Protocol: the checkpoint is restored to HOST arrays
    (topology-independently, via the saved metadata), leaves are matched
    to ``target``'s by tree path, adapted where the layout is
    device-count-dependent, and committed to each target leaf's
    sharding on the new mesh
    (:func:`..parallel.multihost.commit_to_mesh`).

    The one device-count-dependent layout in the framework is ZeRO-1's
    flattened-padded optimizer state: each leaf is 1-D, zero-padded to
    a multiple of the data-axis size N (``parallel/zero1.py``).  On a
    device-count change the pad length changes, so saved flat leaves
    are trimmed/re-padded to the target's length — sound because the
    pad region is identically zero and inert through every elementwise
    update rule (both lengths are >= the real entry count, so no real
    entry is ever cut).  dp (replicated) and fsdp (full global shapes,
    per-leaf shardings) need no adaptation beyond the re-commit.
    """
    from ..parallel.multihost import commit_to_mesh

    faults.fire("resume")
    saved = load_checkpoint(directory, target=None, step=step)
    saved_leaves = _leaves_by_path(saved)
    target_leaves = _leaves_by_path(target)
    missing = set(target_leaves) - set(saved_leaves)
    if missing:
        raise ValueError(
            f"checkpoint at {directory} lacks {len(missing)} leaves the "
            f"restoring state needs (e.g. {sorted(missing)[:3]}) — was it "
            "saved by a different model/optimizer configuration?")

    def adapt(path, t):
        s = np.asarray(saved_leaves[path])
        tshape = tuple(np.shape(t))
        if s.shape != tshape:
            if s.ndim == 1 and len(tshape) == 1:
                # ZeRO-1 flat-padded slot: re-split for the new device
                # count (trim surplus old pad / add new pad — zeros
                # both ways)
                n = min(s.shape[0], tshape[0])
                out = np.zeros(tshape, s.dtype)
                out[:n] = s[:n]
                s = out
            else:
                raise ValueError(
                    f"leaf {'/'.join(path)}: saved shape {s.shape} cannot "
                    f"be adapted to {tshape} — only 1-D (flat-padded "
                    "ZeRO-1) leaves are device-count-dependent; a "
                    "different model/optimizer cannot resume elastically")
        dtype = getattr(t, "dtype", None)
        if dtype is not None and s.dtype != dtype:
            s = s.astype(dtype)
        return commit_to_mesh(s, t)

    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    flat, treedef = tree_flatten_with_path(target)
    out = [adapt(tuple(_path_key(k) for k in path), t) for path, t in flat]
    return tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# RESUME manifest
# ---------------------------------------------------------------------------


def _manifest_path(directory: str) -> str:
    return os.path.join(os.path.abspath(directory), RESUME_MANIFEST)


def write_resume_manifest(directory: str, manifest: dict) -> str:
    """Atomically (write-then-rename) persist the preemption manifest.
    Coordinator-only on multi-host runs; every process may call."""
    path = _manifest_path(directory)
    if jax.process_index() != 0:
        return path
    os.makedirs(os.path.abspath(directory), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def read_resume_manifest(directory: str) -> Optional[dict]:
    """The manifest left by a preempted run, or None (absent/corrupt —
    a half-written manifest can only be pre-rename garbage, which this
    never reads)."""
    try:
        with open(_manifest_path(directory)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None


def clear_resume_manifest(directory: str) -> None:
    """Remove the manifest (a run that COMPLETES must not leave a stale
    mid-run cursor for the next resume to trust)."""
    if jax.process_index() != 0:
        return
    try:
        os.remove(_manifest_path(directory))
    except OSError:
        pass
