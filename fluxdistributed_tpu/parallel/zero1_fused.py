"""Fused ZeRO-1 weight update: pack → ONE reduce-scatter → ONE fused
Adam kernel → ONE all-gather.

``zero1.make_train_step_zero1_shardmap`` executes the paper's schedule
(arXiv:2004.13336) faithfully, but as a *per-leaf* composition: every
parameter leaf gets its own reduce-scatter, its own chain of Adam
element ops (2 multiplies + 2 FMAs + rsqrt + divide + subtract, each a
separate HLO with its own HBM round-trip unless fusion wins), and its
own all-gather.  On a transformer that is hundreds of small collectives
and kernels per step — exactly the launch/latency overhead the
full-program-compilation premise (arXiv:1810.09868) says to fuse away.

This module collapses the whole update into four programs, total:

1. **pack** — every gradient leaf is raveled, cast to f32, and
   concatenated into ONE flat buffer, zero-padded so it splits evenly
   over the data axis (pad entries are inert through Adam: zero grad →
   zero momentum → zero delta);
2. **one reduce-scatter** on that buffer (vs one per leaf) — each
   device receives the summed 1/N slice;
3. **one fused Adam kernel** (``ops``-style Pallas, NEW
   ``fused_adam_update``) over the local slice: p/g/m/v stream through
   VMEM once, the full m/v/p̂ chain runs on the VPU between the loads
   and the stores — 4 reads + 3 writes of HBM, the streaming minimum;
4. **one all-gather**, then unpack back to leaf shapes.

Off TPU the kernel body runs as the identical jnp expression (the
"xla" impl — same math, same f32 accumulation, so CPU tests pin
bit-for-bit parity against ``make_train_step_zero1``), and the Pallas
interpreter covers the real kernel code in the slow tier.

Optimizer state is two flat f32 buffers (``{"m", "v"}``) sharded
``P(data)`` — checkpointing sees an ordinary (if flat) state tree.
The update math is bakes-Adam-only by design: the fusion IS the rule.
For other rules use the composable ``zero1`` variants.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import mesh as mesh_lib
from ..ops.pallas_attention import interpret_mode
from . import collectives, dp

__all__ = [
    "fused_adam_update",
    "pack_tree",
    "unpack_tree",
    "zero1_fused_state",
    "make_train_step_zero1_fused",
]

_LANES = 128
_SUBLANES = 8
#: the packed buffer pads to a multiple of (shards × one f32 tile) so
#: every device's slice reshapes to whole [8, 128] VPU tiles
_TILE = _LANES * _SUBLANES

def _resolve_impl(impl: str | None) -> str:
    """``None``/``"auto"`` → compiled kernel on TPU, the identical-math
    XLA expression elsewhere; ``"interpret"`` runs the real kernel under
    the Pallas interpreter (the CPU kernel-parity tests)."""
    if impl in (None, "auto"):
        return "pallas" if not interpret_mode() else "xla"
    if impl not in ("pallas", "interpret", "xla"):
        raise ValueError(f"unknown impl {impl!r} (pallas|interpret|xla|auto)")
    return impl


def _is_none(x):
    return x is None


# ---------------------------------------------------------------------------
# pack / unpack
# ---------------------------------------------------------------------------


def packed_size(params, nshards: int) -> int:
    """Flat f32 element count of the packed tree, padded to split into
    whole VPU tiles per shard."""
    total = sum(l.size for l in jax.tree.leaves(params, is_leaf=_is_none)
                if l is not None)
    return total + (-total) % (nshards * _TILE)


def pack_tree(tree, nshards: int) -> jax.Array:
    """Ravel + concat every (non-``None``) leaf into one padded f32
    buffer — the single operand the collectives and the kernel see."""
    leaves = [l for l in jax.tree.leaves(tree, is_leaf=_is_none)
              if l is not None]
    flat = jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for l in leaves])
    pad = (-flat.size) % (nshards * _TILE)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat


def unpack_tree(flat: jax.Array, template):
    """Invert :func:`pack_tree` against ``template``'s shapes/dtypes
    (the pad tail is dropped)."""
    leaves, treedef = jax.tree.flatten(template, is_leaf=_is_none)
    out, off = [], 0
    for leaf in leaves:
        if leaf is None:
            out.append(None)
            continue
        out.append(flat[off:off + leaf.size].reshape(leaf.shape)
                   .astype(leaf.dtype))
        off += leaf.size
    return treedef.unflatten(out)


# ---------------------------------------------------------------------------
# the fused kernel
# ---------------------------------------------------------------------------


def _adam_kernel(sc_ref, p_ref, g_ref, m_ref, v_ref,
                 po_ref, mo_ref, vo_ref, *, b1, b2, eps):
    """One [block, 128] tile of the fused Adam chain — the entire
    m/v/bias-correct/apply sequence between one set of loads and one
    set of stores.  ``sc_ref`` (scalar-prefetch): [eta, c1, c2] f32 —
    the step-dependent scalars, data so LR schedules never retrace."""
    eta, c1, c2 = sc_ref[0], sc_ref[1], sc_ref[2]
    g = g_ref[:]
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * (g * g)
    mhat = m / c1
    vhat = v / c2
    po_ref[:] = p_ref[:] - eta * mhat / (jnp.sqrt(vhat) + eps)
    mo_ref[:] = m
    vo_ref[:] = v


@functools.partial(
    jax.jit, static_argnames=("b1", "b2", "eps", "impl", "block_rows"))
def _fused_adam_impl(p, g, m, v, scalars, b1, b2, eps, impl, block_rows):
    n = p.shape[0]
    if impl == "xla":
        # the kernel body as one XLA expression — identical math (and
        # the op order of optim.adam's step_leaf, so parity with the
        # composable ZeRO-1 variants is exact)
        eta, c1, c2 = scalars[0], scalars[1], scalars[2]
        m2 = b1 * m + (1.0 - b1) * g
        v2 = b2 * v + (1.0 - b2) * (g * g)
        p2 = p - eta * (m2 / c1) / (jnp.sqrt(v2 / c2) + eps)
        return p2, m2, v2

    rows = n // _LANES
    # block_rows must DIVIDE rows or the grid would drop the tail rows
    # (leaving uninitialized p'/m'/v' to be all-gathered into params).
    # rows is a multiple of _SUBLANES by the pack alignment, so stepping
    # down in whole sublanes always terminates at a valid tile-aligned
    # divisor (worst case _SUBLANES itself).
    block_rows = max(min(block_rows, rows) // _SUBLANES * _SUBLANES,
                     _SUBLANES)
    while rows % block_rows:
        block_rows -= _SUBLANES
    shape2 = (rows, _LANES)
    spec = pl.BlockSpec((block_rows, _LANES), lambda i, sc: (i, 0))
    p2, m2, v2 = pl.pallas_call(
        functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(rows // block_rows,),
            in_specs=[spec] * 4,
            out_specs=[spec] * 3,
        ),
        out_shape=[jax.ShapeDtypeStruct(shape2, jnp.float32)] * 3,
        interpret=impl == "interpret",
    )(scalars, p.reshape(shape2), g.reshape(shape2),
      m.reshape(shape2), v.reshape(shape2))
    return p2.reshape(n), m2.reshape(n), v2.reshape(n)


def fused_adam_update(
    p: jax.Array,
    g: jax.Array,
    m: jax.Array,
    v: jax.Array,
    step,
    *,
    lr=1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    impl: str | None = None,
    block_rows: int = 512,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The fused Adam step over flat f32 buffers (a local ZeRO-1 shard):
    ``(p', m', v')``.  ``lr`` may be a schedule (callable on ``step``);
    the step-dependent scalars ride as DATA so nothing retraces across
    steps.  Buffer length must be a multiple of 1024 (whole VPU tiles —
    :func:`pack_tree` guarantees it)."""
    if p.shape[0] % _TILE:
        raise ValueError(
            f"fused_adam_update needs whole [{_SUBLANES}, {_LANES}] tiles: "
            f"length {p.shape[0]} is not a multiple of {_TILE} "
            "(pack with pack_tree)")
    eta = lr(step) if callable(lr) else lr
    t = jnp.asarray(step, jnp.float32) + 1.0
    scalars = jnp.stack([
        jnp.asarray(eta, jnp.float32),
        1.0 - jnp.power(jnp.float32(b1), t),
        1.0 - jnp.power(jnp.float32(b2), t),
    ])
    return _fused_adam_impl(
        p.astype(jnp.float32), g.astype(jnp.float32),
        m.astype(jnp.float32), v.astype(jnp.float32), scalars,
        b1=b1, b2=b2, eps=eps, impl=_resolve_impl(impl),
        block_rows=block_rows)


# ---------------------------------------------------------------------------
# the train step
# ---------------------------------------------------------------------------


def zero1_fused_state(
    params,
    mesh: Mesh,
    axis: str = mesh_lib.DATA_AXIS,
    model_state=None,
) -> tuple[dp.TrainState, dp.TrainState]:
    """Create and place the fused-update ``TrainState``: params and
    model state replicated, optimizer state as TWO flat f32 buffers
    (``m``/``v`` over the packed layout) sharded 1/N over ``axis`` —
    the same memory win as ``zero1_state``, minus the per-leaf tree."""
    from ..sharding import unaliased

    n = mesh.shape[axis]
    size = packed_size(params, n)
    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    # unaliased: device_put onto the resident layout can return the
    # CALLER's buffers — a donated step would then delete them under
    # the caller (the same guard zero1_state uses)
    state = dp.TrainState(
        params=jax.tree.map(
            lambda x: None if x is None else jax.device_put(
                unaliased(x), repl),
            params, is_leaf=_is_none),
        opt_state={
            "m": jax.device_put(jnp.zeros((size,), jnp.float32), shard),
            "v": jax.device_put(jnp.zeros((size,), jnp.float32), shard),
        },
        model_state=jax.tree.map(
            lambda x: jax.device_put(unaliased(x), repl), model_state or {}),
        step=jax.device_put(jnp.zeros((), jnp.int32), repl),
    )
    shardings = dp.TrainState(
        params=jax.tree.map(lambda _: repl, state.params, is_leaf=_is_none),
        opt_state={"m": shard, "v": shard},
        model_state=jax.tree.map(lambda _: repl, state.model_state),
        step=repl,
    )
    return state, shardings


def make_train_step_zero1_fused(
    loss_fn: Callable,
    mesh: Mesh,
    state: dp.TrainState,
    *,
    lr=1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    axis: str = mesh_lib.DATA_AXIS,
    donate: bool = True,
    seed: int = 0,
    impl: str | None = None,
):
    """ZeRO-1 with the fused packed update: per device inside ONE
    ``shard_map`` — local grads on the batch shard → pack the whole
    gradient tree flat → ONE reduce-scatter → the fused Adam kernel on
    this device's slice → ONE all-gather → unpack.  Numerically the
    same summed-gradient Adam step as ``make_train_step_zero1`` (in
    f32; an f32 model matches bit-for-bit), at collective/kernel counts
    independent of the number of parameter leaves.

    ``state`` comes from :func:`zero1_fused_state` and fixes the spec
    tree; ``lr`` may be a schedule.
    """
    nshards = mesh.shape[axis]
    with_rng = dp._accepts_rng(loss_fn)
    repl_spec = P()
    shard_spec = P(axis)
    state_specs = dp.TrainState(
        params=jax.tree.map(lambda _: repl_spec, state.params,
                            is_leaf=_is_none),
        opt_state={"m": shard_spec, "v": shard_spec},
        model_state=jax.tree.map(lambda _: repl_spec, state.model_state),
        step=repl_spec,
    )
    from ..compat import LEGACY_SHARD_MAP

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(state_specs, shard_spec),
        out_specs=(state_specs, repl_spec),
        check_vma=False,
    )
    def step(state: dp.TrainState, batch):
        def lossf(params):
            if with_rng:
                rng = jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(seed), state.step),
                    jax.lax.axis_index(axis),
                )
                return loss_fn(params, state.model_state, batch, True, rng=rng)
            return loss_fn(params, state.model_state, batch, True)

        (loss, (new_mstate, _)), grads = jax.value_and_grad(
            lossf, has_aux=True)(state.params)
        loss = jax.lax.pmean(loss, axis)
        new_mstate = collectives.pmean(new_mstate, axis)
        flat_g = pack_tree(grads, nshards)
        i = jax.lax.axis_index(axis)
        chunk = flat_g.shape[0] // nshards
        if LEGACY_SHARD_MAP:
            # ONE collective for the whole tree (the fusion's wire half)
            flat_g = collectives.reduce_scatter({"g": flat_g}, axis)["g"]
        else:
            # VMA tracers psummed the replicated-param cotangent already
            flat_g = jax.lax.dynamic_slice_in_dim(flat_g, i * chunk, chunk)
        flat_g = flat_g / nshards
        flat_p = jax.lax.dynamic_slice_in_dim(
            pack_tree(state.params, nshards), i * chunk, chunk)
        p2, m2, v2 = fused_adam_update(
            flat_p, flat_g, state.opt_state["m"], state.opt_state["v"],
            state.step, lr=lr, b1=b1, b2=b2, eps=eps, impl=impl)
        gathered = collectives.all_gather({"p": p2}, axis)["p"]
        new_params = unpack_tree(gathered, state.params)
        new_state = dp.TrainState(
            params=new_params,
            opt_state={"m": m2, "v": v2},
            model_state=new_mstate,
            step=state.step + 1,
        )
        return new_state, {"loss": loss}

    return jax.jit(step, donate_argnums=(0,) if donate else ())
