#!/usr/bin/env python
"""Convergence acceptance run: ResNet-34 / CIFAR-10-format data.

Evidence that the FULL stack learns — binary dataset parsing → registry
→ prefetch loader → compiled DP train step (bf16 on TPU) → compiled
eval with top-k — not merely that steps execute.  The BASELINE.json
"ResNet-34/CIFAR-10 (CPU ref)" config.

This container has no network, so real CIFAR-10 can't be fetched; by
default the script synthesizes a *learnable* dataset in the exact CIFAR
binary layout (1 label byte + 3072 CHW bytes per record: class template
+ noise, 10 classes) and loads it through the real ``cifar10`` registry
driver.  Point ``--data`` at a real ``cifar-10-batches-bin`` directory
to run the true dataset; everything downstream is identical.

Prints per-eval {step, loss, val_top1} lines and a final JSON summary.

Usage: python benchmarks/convergence.py [--cycles 300] [--batch 128]
       [--data DIR] [--platform cpu] [--json-out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile

import numpy as np


def synth_cifar_binaries(root: str, n_train: int = 10000, n_test: int = 2000,
                         seed: int = 0, noise: float = 0.25) -> None:
    """Write a learnable 10-class dataset in the CIFAR-10 binary format."""
    rng = np.random.default_rng(seed)
    templates = rng.normal(0, 1, (10, 32, 32, 3)).astype(np.float32)
    # low-pass the templates so classes are distinguishable after crops
    for _ in range(2):
        templates = (
            templates
            + np.roll(templates, 1, 1) + np.roll(templates, -1, 1)
            + np.roll(templates, 1, 2) + np.roll(templates, -1, 2)
        ) / 5.0

    def write(path: str, n: int):
        labels = rng.integers(0, 10, n).astype(np.uint8)
        x = templates[labels] + rng.normal(0, noise, (n, 32, 32, 3)).astype(np.float32)
        x = (x - x.min()) / (np.ptp(x) + 1e-9)
        imgs = (x * 255).astype(np.uint8).transpose(0, 3, 1, 2)  # HWC→CHW
        rec = np.concatenate(
            [labels[:, None], imgs.reshape(n, 3072)], axis=1
        ).astype(np.uint8)
        rec.tofile(path)

    per = n_train // 5
    for i in range(1, 6):
        write(os.path.join(root, f"data_batch_{i}.bin"), per)
    write(os.path.join(root, "test_batch.bin"), n_test)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cycles", type=int, default=300)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--eval-every", type=int, default=50)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--data", default=None, help="real cifar-10-batches-bin dir")
    ap.add_argument("--platform", default=None)
    ap.add_argument("--json-out", default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import shutil

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    if args.data:
        root = args.data
        synthetic = False
    else:
        root = tempfile.mkdtemp(prefix="cifar_synth_")
        synth_cifar_binaries(root)
        synthetic = True

    try:
        run(args, root, synthetic)
    finally:
        if synthetic:
            shutil.rmtree(root, ignore_errors=True)


def run(args, root: str, synthetic: bool):
    import jax

    from fluxdistributed_tpu import optim
    from fluxdistributed_tpu.data.registry import open_dataset, register_dataset
    from fluxdistributed_tpu.models import resnet34
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.logging import Logger

    register_dataset("cifar_conv", "cifar10", path=root, split="train")
    register_dataset("cifar_conv_val", "cifar10", path=root, split="test")
    ds = open_dataset("cifar_conv")
    val = open_dataset("cifar_conv_val")

    history: list[dict] = []

    class Recorder(Logger):
        def log(self, metrics: dict, step=None):
            row = {"step": int(step or 0), **{k: float(v) for k, v in metrics.items()}}
            history.append(row)
            if "val_top1" in metrics or "train_step_loss" in metrics:
                print(json.dumps(row), flush=True)

        def info(self, msg: str):
            print(msg, flush=True)

    task = prepare_training(
        resnet34(num_classes=10),
        ds,
        optim.momentum(
            optim.warmup_cosine(args.lr, min(50, args.cycles // 5), args.cycles), 0.9
        ),
        batch_size=args.batch,
        cycles=args.cycles,
        val_dataset=val,
        val_samples=512,
        seed=args.seed,
        topk=(1, 5),
        input_shape=(32, 32, 3),
    )
    rec = Recorder()
    train(
        task,
        print_every=max(args.cycles // 10, 1),
        eval_every=args.eval_every,
        topk=(1, 5),
        logger=rec,
    )
    # final eval on the FINISHED model — the in-loop cadence can be up to
    # eval_every-1 steps stale relative to the returned weights
    from fluxdistributed_tpu.train.trainer import _eval_and_log

    _eval_and_log(task, task.val_batch, "val", args.cycles, (1, 5), rec)

    evals = [h for h in history if "val_top1" in h]
    summary = {
        "metric": "ResNet-34/CIFAR-10-format convergence",
        "dataset": "synthetic-cifar-binary" if synthetic else "cifar10",
        "cycles": args.cycles,
        "global_batch": args.batch,
        "first_val_top1": evals[0]["val_top1"] if evals else None,
        "final_val_top1": evals[-1]["val_top1"] if evals else None,
        "final_val_loss": evals[-1]["val_loss"] if evals else None,
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(summary))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump({"summary": summary, "history": history}, f, indent=1)


if __name__ == "__main__":
    main()
