"""FDT106 positive: metric names off the fdtpu_* convention."""

PREFIX = "serve_"  # resolves, but misses the fdtpu_ prefix


def register(reg):
    reg.counter("serve_requests_total")  # missing prefix
    reg.gauge("Fdtpu_queue_depth")  # wrong case
    reg.histogram("fdtpu-step-seconds")  # dashes
    reg.counter(PREFIX + "rejected_total")  # resolved concat, bad prefix
    reg.gauge(f"{PREFIX}depth")  # resolved f-string, bad prefix


def register_aliased(reg):
    r, p = reg, PREFIX  # the scheduler's tuple-unpack prefix idiom
    r.counter(p + "finished_total")  # resolves through the alias chain
