"""Image decode + preprocessing for ImageNet-style training.

Replaces the reference's preprocessing stack (src/preprocess.jl):
``resize_smallest_dimension`` 256 with a Gaussian lowpass when
downscaling (:30-42), ``center_crop`` 224 (:45-49), mean/std ImageNet
normalization and CHW→WHCN permute (:51-67).  Here decode and resize run
on host CPU via PIL (JPEG decode stays host-side on TPU too — SURVEY §2
native-dep table), arrays are NHWC float32, and the device copy happens
in the prefetch loader.

**The double-normalize quirk.**  The reference multiplies the normalized
image by 255 (src/preprocess.jl:66) and then ``fproc`` re-standardizes
each image with ``Flux.normalise`` (src/imagenet.jl:34), so the de-facto
training distribution is per-image zero-mean/unit-var — the ImageNet
mean/std wash out.  The clean behavior (resize → crop → (x-μ)/σ) is the
default here; ``compat_double_normalize=True`` reproduces the
reference's exact pipeline for parity testing.
"""

from __future__ import annotations

import io
from typing import Sequence

import numpy as np

__all__ = [
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "decode_image",
    "resize_smallest_dimension",
    "center_crop",
    "preprocess",
    "sample_augment_params",
    "random_resized_crop",
]

# Reference constants, src/preprocess.jl:51-53
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def decode_image(src) -> np.ndarray:
    """JPEG/PNG bytes, path, or file-like → RGB uint8 HWC array.

    The ``jpeg_decode`` analog (src/imagenet.jl:32, via libjpeg-turbo);
    PIL uses libjpeg on the host here.
    """
    from PIL import Image

    if isinstance(src, (bytes, bytearray)):
        src = io.BytesIO(src)
    img = Image.open(src)
    if img.mode != "RGB":
        img = img.convert("RGB")  # handles grayscale/CMYK ImageNet files
    return np.asarray(img, np.uint8)


def resize_smallest_dimension(img: np.ndarray, size: int = 256) -> np.ndarray:
    """Scale so the smallest side equals ``size`` (aspect preserved).

    The reference lowpass-filters with a Gaussian before downscaling
    (src/preprocess.jl:30-42, ``imfilter`` + ``imresize``); PIL's
    ``BILINEAR`` with ``reducing_gap`` performs the equivalent
    antialiased area reduction.
    """
    from PIL import Image

    h, w = img.shape[:2]
    scale = size / min(h, w)
    nh, nw = max(size, round(h * scale)), max(size, round(w * scale))
    pil = Image.fromarray(img)
    pil = pil.resize((nw, nh), Image.BILINEAR, reducing_gap=2.0)
    return np.asarray(pil, np.uint8)


def center_crop(img: np.ndarray, size: int = 224) -> np.ndarray:
    """Central ``size``×``size`` crop (src/preprocess.jl:45-49)."""
    h, w = img.shape[:2]
    top = (h - size) // 2
    left = (w - size) // 2
    return img[top : top + size, left : left + size]


def sample_augment_params(rng: np.random.Generator, n: int) -> np.ndarray:
    """Sample (n, 5) train-augmentation parameters: ``(area_frac,
    log_ratio→ratio, u, v, flip)`` — the torchvision RandomResizedCrop
    distribution (scale 0.08–1.0, aspect 3/4–4/3) + p=0.5 hflip.

    Parameters are RELATIVE so they can be sampled before image
    dimensions are known; the executor (Python or native C++) converts
    them to a pixel rect after decode.  Keeping the RNG in Python keeps
    the native pipeline deterministic and both paths reproducible from
    the same draw.
    """
    area = rng.uniform(0.08, 1.0, n)
    ratio = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3), n))
    u = rng.uniform(0, 1, n)
    v = rng.uniform(0, 1, n)
    flip = (rng.uniform(0, 1, n) < 0.5).astype(np.float64)
    return np.stack([area, ratio, u, v, flip], axis=1).astype(np.float32)


def _aug_rect(h: int, w: int, area: float, ratio: float, u: float, v: float):
    """Pixel crop rect from relative params (shared contract with the
    native implementation — keep in sync with fd_native.cpp aug_rect)."""
    target = area * h * w
    cw = int(round(np.sqrt(target * ratio)))
    ch = int(round(np.sqrt(target / ratio)))
    if cw < 1 or ch < 1 or cw > w or ch > h:
        # fallback: largest centered square (torchvision's fallback is
        # a center crop of the min side)
        side = min(h, w)
        return (h - side) // 2, (w - side) // 2, side, side
    y0 = int(round(v * (h - ch)))
    x0 = int(round(u * (w - cw)))
    return y0, x0, ch, cw


def random_resized_crop(img: np.ndarray, crop: int, params) -> np.ndarray:
    """Apply one ``sample_augment_params`` row: crop the sampled rect,
    resize to ``crop``×``crop``, horizontal-flip if flagged."""
    from PIL import Image

    area, ratio, u, v, flip = (float(p) for p in params)
    h, w = img.shape[:2]
    y0, x0, ch, cw = _aug_rect(h, w, area, ratio, u, v)
    region = img[y0 : y0 + ch, x0 : x0 + cw]
    pil = Image.fromarray(region).resize((crop, crop), Image.BILINEAR, reducing_gap=2.0)
    out = np.asarray(pil, np.uint8)
    if flip >= 0.5:
        out = out[:, ::-1]
    return out


def preprocess(
    img,
    crop: int = 224,
    resize: int = 256,
    mean: Sequence[float] = IMAGENET_MEAN,
    std: Sequence[float] = IMAGENET_STD,
    compat_double_normalize: bool = False,
    augment=None,
) -> np.ndarray:
    """Full pipeline: decode (if needed) → resize → crop → normalize.

    ``augment``: an optional ``sample_augment_params`` row switching the
    geometric stage to RandomResizedCrop+flip (train mode); the default
    is the eval/reference path (resize smallest side → center crop).

    Returns HWC float32 (NHWC once batched) — the TPU-native layout; the
    reference's WHCN permute (src/preprocess.jl:64-65) is a Julia
    memory-order artifact with no analog here.
    """
    if not isinstance(img, np.ndarray):
        img = decode_image(img)
    # area <= 0 means "no augmentation" — the same gate the native
    # executor applies (fd_native.cpp: `aug && aug[0] > 0.f`), so
    # degenerate rows behave identically on both backends.
    if augment is not None and float(augment[0]) > 0:
        img = random_resized_crop(img, crop, augment)
    else:
        img = resize_smallest_dimension(img, resize)
        img = center_crop(img, crop)
    x = img.astype(np.float32) / 255.0
    x = (x - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)
    if compat_double_normalize:
        # Reference quirk: .* 255 after normalizing (src/preprocess.jl:66)
        # then per-image standardization (Flux.normalise, src/imagenet.jl:34).
        x = x * 255.0
        x = (x - x.mean()) / (x.std() + 1e-5)
    return x
