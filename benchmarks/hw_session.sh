#!/bin/sh
# Staged hardware-benchmark session: run the full perf chain the moment
# the tunneled chip answers, ONE TPU client at a time.
#
# Timeout policy: every stage runs under `timeout` with LARGE headroom
# (>= 3x the worst observed compile+run). Killing a live TPU client can
# wedge the axon device grant server-side — but an unbounded hang in
# backend init (observed: 25-35 min before an explicit UNAVAILABLE)
# would stall the whole session forever. The bounds below only fire in
# that hung-init mode, where the grant was never acquired; they are
# deliberately far above any healthy stage duration. Do NOT kill stages
# by hand.
#
# Deadline policy: when HW_DEADLINE_EPOCH is set (hw_watch.sh exports
# it), each stage launches only if its FULL timeout bound fits before
# the deadline — the stage boundary is the kill-free safe point, so a
# session can never hold the one-client grant into the driver's
# official bench window. Skipped stages are logged, not silently lost.
#
#   sh benchmarks/hw_session.sh [outdir]          # default benchmarks/hw
#
# Each stage appends to its own file so a mid-session outage loses
# nothing; stages are leverage-ordered (VERDICT r3: bench first, then
# sweep, then trace, then LM + ingest).
set -u
cd "$(dirname "$0")/.."
OUT="${1:-benchmarks/hw}"
mkdir -p "$OUT"
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
DEADLINE="${HW_DEADLINE_EPOCH:-0}"

fits() { # fits <seconds>: does a stage bounded at <seconds> fit?
    [ "$DEADLINE" = 0 ] && return 0
    if [ $(( $(date +%s) + $1 )) -gt "$DEADLINE" ]; then
        echo "[$(stamp)] skipping next stage: its ${1}s bound would straddle the deadline" | tee -a "$OUT/session.log"
        return 1
    fi
    return 0
}

echo "[$(stamp)] 1/10 headline bench" | tee -a "$OUT/session.log"
fits 3000 && timeout 3000 python bench.py >> "$OUT/bench.jsonl" 2>> "$OUT/session.log"

echo "[$(stamp)] 2/10 step sweep (leverage-ordered; fuse rows isolate tunnel dispatch)" | tee -a "$OUT/session.log"
# no outer timeout: every sweep child self-bounds at 1800s and the
# parent stops between children once SWEEP_DEADLINE_EPOCH approaches —
# killing the parent would orphan a TPU child still holding the grant
fits 1800 && SWEEP_DEADLINE_EPOCH="$DEADLINE" python benchmarks/step_sweep.py >> "$OUT/sweep.jsonl" 2>> "$OUT/session.log"

echo "[$(stamp)] 3/10 trace analysis" | tee -a "$OUT/session.log"
fits 3600 && timeout 3600 python benchmarks/trace_analysis.py >> "$OUT/trace.txt" 2>> "$OUT/session.log"

echo "[$(stamp)] 4/10 step segments + cost analysis" | tee -a "$OUT/session.log"
fits 3600 && timeout 3600 python benchmarks/train_step_segments.py >> "$OUT/segments.txt" 2>> "$OUT/session.log"

echo "[$(stamp)] 5/10 LM benches" | tee -a "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/lm_bench.py --model lm_small --seqlen 1024 >> "$OUT/lm.jsonl" 2>> "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/lm_bench.py --model lm_small --seqlen 2048 >> "$OUT/lm.jsonl" 2>> "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/lm_bench.py --model lm_medium --seqlen 1024 --batch 8 >> "$OUT/lm.jsonl" 2>> "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/lm_bench.py --model lm_medium --seqlen 1024 --batch 8 --remat >> "$OUT/lm.jsonl" 2>> "$OUT/session.log"
# Pallas flash fwd+bwd vs XLA blockwise through the FULL train step at
# long T (VERDICT r4 #3: the kernel must earn its keep on hardware)
fits 2700 && timeout 2700 python benchmarks/lm_bench.py --model lm_small --seqlen 2048 --attn flash >> "$OUT/lm.jsonl" 2>> "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/lm_bench.py --model lm_small --seqlen 2048 --attn blockwise >> "$OUT/lm.jsonl" 2>> "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/lm_bench.py --model lm_small --seqlen 4096 --batch 8 --attn flash >> "$OUT/lm.jsonl" 2>> "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/lm_bench.py --model lm_small --seqlen 4096 --batch 8 --attn blockwise >> "$OUT/lm.jsonl" 2>> "$OUT/session.log"
# round-5 attention features on hardware: windowed flash (O(T*W) block
# skipping) and GQA (grouped KV, kv-heads=3 divides lm_small's 12 heads)
fits 2700 && timeout 2700 python benchmarks/lm_bench.py --model lm_small --seqlen 4096 --batch 8 --attn flash --window 1024 >> "$OUT/lm.jsonl" 2>> "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/lm_bench.py --model lm_small --seqlen 2048 --attn flash --kv-heads 3 >> "$OUT/lm.jsonl" 2>> "$OUT/session.log"

echo "[$(stamp)] 6/10 end-to-end ingest" | tee -a "$OUT/session.log"
fits 3600 && timeout 3600 python benchmarks/ingest_e2e.py --steps 20 >> "$OUT/ingest.jsonl" 2>> "$OUT/session.log"
fits 3600 && timeout 3600 python benchmarks/ingest_e2e.py --steps 20 --s2d >> "$OUT/ingest.jsonl" 2>> "$OUT/session.log"

echo "[$(stamp)] 7/10 attention-core microbench (incl. windowed-flash row)" | tee -a "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/attention_bench.py --window 1024 >> "$OUT/attention.jsonl" 2>> "$OUT/session.log"
# flash-DECODE kernels on hardware (first compiled-Pallas decode rows:
# dense cursor-skip / windowed ring+sinks / paged page-table walk vs the
# engine's XLA gather+mask path — CPU fallback rows in docs/benchmarks.md)
fits 2700 && timeout 2700 python benchmarks/attention_bench.py --decode --max-len 4096 --live 512 >> "$OUT/attention.jsonl" 2>> "$OUT/session.log"

# serving decode: continuous batching vs sequential generate at
# C={1,4,16} (CPU rows recorded in docs/benchmarks.md; these are the
# first TPU rows — lm_small realistic-vocab, then the windowed config).
# Every run also emits the paged-vs-dense layout rows (KV bytes per
# live token + short-TTFT-behind-long-prompt); the third run sizes a
# realistic paged pool to put real HBM numbers behind the CPU ratios.
echo "[$(stamp)] 8/10 decode / serving bench" | tee -a "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/decode_bench.py --model lm_small --vocab 32000 --prompt-len 128 --new-tokens 256 >> "$OUT/decode.jsonl" 2>> "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/decode_bench.py --model lm_small --vocab 32000 --prompt-len 128 --new-tokens 256 --window 1024 --sinks 4 >> "$OUT/decode.jsonl" 2>> "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/decode_bench.py --model lm_small --vocab 32000 --prompt-len 256 --new-tokens 256 --kv-block-size 32 --prefill-chunk 128 --kv-blocks 96 >> "$OUT/decode.jsonl" 2>> "$OUT/session.log"

# pipeline planner + zero-bubble: planned-vs-uniform and 1f1b-vs-zb row
# pairings on real chips (the first rows where the measured bubble is
# actual idle-device time, not the CPU mesh's fixed-overhead proxy).
# depth 32 keeps V >= 2 blocks/stage up to a 16-device slice (planner
# freedom; a 1-chip grant declines fast with a clear no-pipe-axis
# error), and the realistic 32k vocab makes the embed/head skew the
# planner exists to fix actually present.  The profile artifact feeds
# later --profile replays and --pp-plan runs.
echo "[$(stamp)] 9/10 pipeline planner / zero-bubble bench" | tee -a "$OUT/session.log"
fits 2700 && timeout 2700 python benchmarks/pp_bubble.py --schedule 1f1b --plan auto --with-zb --depth 32 --vocab 32000 --seconds 5 --profile-out "$OUT/pp_profile.json" >> "$OUT/pp.jsonl" 2>> "$OUT/session.log"

# auto-layout picker on the real topology: price every dp x fsdp x tp
# candidate against the chip's ACTUAL bytes_limit (no --hbm-bytes
# needed on hardware), train a few cycles with the chosen layout, and
# keep the ranking artifact — the first hardware row where "fit this
# model on this topology" is one flag (parallel/layout.py; CPU-mesh
# rankings live in tests/test_layout.py and the CI report)
echo "[$(stamp)] 10/10 auto-layout picker + rule-derived training" | tee -a "$OUT/session.log"
fits 2700 && timeout 2700 python bin/driver.py --model lm_small --dataset synthetic-text --vocab 32000 --seqlen 1024 --batch-size 32 --cycles 20 --layout auto --layout-report "$OUT/layout_pick.json" >> "$OUT/layout.jsonl" 2>> "$OUT/session.log"

echo "[$(stamp)] session complete (incl. decode + pp planner + layout pick)" | tee -a "$OUT/session.log"
