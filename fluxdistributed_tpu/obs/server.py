"""Stdlib HTTP ``/metrics`` + ``/healthz`` for training runs.

The LM server proved the pattern (serve/server.py: ThreadingHTTPServer,
no dependencies); this reuses it for the TRAINER so a long-running
``bin/driver.py --metrics-port 9100`` run is scrapeable like the
serving tier:

* ``GET /metrics``  — Prometheus text exposition of a registry;
* ``GET /healthz``  — liveness JSON from a caller hook (the driver
  reports step progress and watchdog state), 200/503 on ``ok``.

The server runs ``serve_forever`` on a daemon thread; ``stop()`` (or
letting the process exit) tears it down.  Handler threads only READ the
registry, so scraping never blocks a training step.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional

from .metrics import Registry, get_registry

__all__ = ["MetricsServer", "start_metrics_server"]


class MetricsServer:
    """One registry + optional health hook behind stdlib HTTP."""

    def __init__(
        self,
        registry: Optional[Registry] = None,
        health_fn: Optional[Callable[[], dict]] = None,
    ):
        self.registry = registry or get_registry()
        self.health_fn = health_fn
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def make_handler(self):
        import http.server

        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # scrapes are not log lines
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200, outer.registry.prometheus_text().encode(),
                               "text/plain; version=0.0.4")
                elif self.path == "/healthz":
                    body = {"ok": True}
                    if outer.health_fn is not None:
                        try:
                            body = dict(outer.health_fn())
                        except Exception as e:  # noqa: BLE001 — a broken
                            # health hook IS an unhealthy report
                            body = {"ok": False,
                                    "error": f"{type(e).__name__}: {e}"}
                    code = 200 if body.get("ok", True) else 503
                    self._send(code, json.dumps(body).encode(),
                               "application/json")
                else:
                    self._send(404, b'{"error": "not found"}',
                               "application/json")

        return Handler

    def start(self, host: str = "0.0.0.0", port: int = 9100):
        """Bind + serve on a daemon thread; returns the underlying
        ``ThreadingHTTPServer`` (its ``server_address[1]`` is the bound
        port — pass ``port=0`` for an ephemeral one in tests)."""
        import http.server

        if self._httpd is not None:
            return self._httpd
        self._httpd = http.server.ThreadingHTTPServer(
            (host, port), self.make_handler()
        )
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fdtpu-metrics-http",
            daemon=True,
        )
        self._thread.start()
        return self._httpd

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def start_metrics_server(
    host: str = "0.0.0.0",
    port: int = 9100,
    registry: Optional[Registry] = None,
    health_fn: Optional[Callable[[], dict]] = None,
) -> MetricsServer:
    """One-call wiring: build + start; returns the :class:`MetricsServer`
    (``.port`` for the bound port, ``.stop()`` to tear down)."""
    srv = MetricsServer(registry=registry, health_fn=health_fn)
    srv.start(host, port)
    return srv
