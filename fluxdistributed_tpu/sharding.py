"""Sharding helpers: replication, batch sharding, partition rules.

TPU-native replacement for the reference's replica/buffer plumbing in
``prepare_training`` (src/ddp_tasks.jl:249-289): where the reference
copies the model onto every GPU (``gpu(resnet)`` per device, :275) and
allocates per-device grad buffers on a HOST GPU (:263-269), here a single
``NamedSharding`` annotation replicates parameters across the mesh and
shards batches along the ``data`` axis — XLA manages placement and
collective insertion.

Also provides regex partition rules for models that shard parameters
(tensor parallel / FSDP-style axes) — scope beyond the reference, but the
mesh plumbing is shared.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

Pytree = Any

__all__ = [
    "P",
    "replicated",
    "axis_size",
    "batch_entry",
    "batch_spec",
    "replicate",
    "shard_batch",
    "partition_by_rules",
    "make_shardings",
    "ensure_synced",
    "stack_on_axis",
]


def replicated(mesh: Mesh) -> NamedSharding:
    """The sharding that puts a full copy on every device."""
    return NamedSharding(mesh, P())


def batch_entry(axis):
    """One PartitionSpec DIM entry for the batch dimension: the axis
    name, or a tuple of names when the batch shards over several mesh
    axes jointly (the 3-D ``(data, fsdp)`` layouts — ``P(("data",
    "fsdp"))`` splits dim 0 over both communicators)."""
    return axis if isinstance(axis, str) else tuple(axis)


def axis_size(mesh: Mesh, axis) -> int:
    """Extent of one axis — or the PRODUCT over a tuple of axes (the
    shard count a multi-axis batch dim splits into)."""
    if isinstance(axis, str):
        return int(mesh.shape[axis])
    size = 1
    for a in axis:
        size *= int(mesh.shape[a])
    return size


def batch_spec(axis=mesh_lib.DATA_AXIS) -> P:
    """PartitionSpec sharding the leading (batch) dimension (``axis``
    may be one mesh axis name or a tuple sharded jointly)."""
    return P(batch_entry(axis))


def unaliased(x):
    """Copy a ``jax.Array`` so a subsequent ``device_put``'s output shares
    no buffer with the caller's array.  ``device_put`` is zero-copy when
    source and target share a device, and train states built from the
    result are *donated* into the compiled step — donation of an aliased
    buffer would delete the caller's array out from under them."""
    import jax.numpy as jnp

    return jnp.array(x, copy=True) if isinstance(x, jax.Array) else x


def replicate(tree: Pytree, mesh: Mesh) -> Pytree:
    """Place a full copy of every leaf on every mesh device.

    Analog of the reference's per-device ``gpu(model)`` / ``gpu(st)``
    replication loop (src/ddp_tasks.jl:273-276) — one annotation instead
    of N copies.
    """
    s = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(unaliased(x), s), tree)


def ensure_synced(tree: Pytree, rtol: float = 0.0, atol: float = 0.0) -> bool:
    """Verify that every device's copy of each replicated leaf is
    identical — the reference's ``ensure_synced`` debug check
    (src/ddp_tasks.jl:115-126, used by its replica-identity tests
    test/single_device.jl:160-167).

    Under ``NamedSharding(P())`` XLA maintains this by construction; the
    check exists for debugging custom sharding code and for tests.  Pulls
    every shard to host — debug/test use only.  Raises AssertionError
    with the offending leaf path on mismatch; returns True otherwise.
    """
    import numpy as np

    from jax.tree_util import tree_flatten_with_path, keystr

    leaves, _ = tree_flatten_with_path(tree)
    for path, leaf in leaves:
        if not isinstance(leaf, jax.Array) or not hasattr(leaf, "addressable_shards"):
            continue
        shards = leaf.addressable_shards
        if len(shards) < 2:
            continue
        # only fully-replicated leaves have whole-array shards everywhere
        if shards[0].data.shape != leaf.shape:
            continue
        ref = np.asarray(shards[0].data)
        for s in shards[1:]:
            got = np.asarray(s.data)
            # equal_nan: identical NaNs (a diverged-but-synced run) are
            # NOT replica divergence — this check is about sharding bugs
            if not np.allclose(ref, got, rtol=rtol, atol=atol, equal_nan=True):
                raise AssertionError(
                    f"replica divergence at {keystr(path)}: device "
                    f"{shards[0].device} vs {s.device}, max abs err "
                    f"{np.abs(ref - got).max()}"
                )
    return True


def stack_on_axis(per_item: Sequence[Pytree], mesh: Mesh, axis: str) -> Pytree:
    """Stack N per-item param trees on a new leading dim sharded over
    ``axis`` — item i's tree lives on device i of the axis.  Shared
    machinery for pipeline stages (``pp.stack_stage_params``) and MoE
    experts (``ep.stack_expert_params``)."""
    import jax.numpy as jnp

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_item)
    sh = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda x: jax.device_put(x, sh), stacked)


def shard_batch(batch: Pytree, mesh: Mesh, axis=mesh_lib.DATA_AXIS) -> Pytree:
    """Shard every array's leading dim across ``axis`` of the mesh
    (one axis name, or a tuple sharded jointly — the 3-D layouts'
    ``("data", "fsdp")`` batch).

    Analog of the reference partitioning the sample table into per-device
    shards (src/ddp_tasks.jl:257-258) + the per-device ``gpu(shard)``
    copies inside the DataLoader closure (:280-282).

    ``batch`` holds the FULL global batch (every host passes the same
    arrays).  Multi-process: each host feeds only its contiguous row
    slice through ``jax.make_array_from_process_local_data`` — no host
    ever materializes another host's shards on device.
    """
    from .parallel.multihost import global_batch_put, local_batch_size

    s = NamedSharding(mesh, batch_spec(axis))
    pi = jax.process_index()

    def put(x):
        x = np.asarray(x) if not isinstance(x, jax.Array) else x
        n = axis_size(mesh, axis)
        if x.shape[0] % n != 0:
            raise ValueError(
                f"batch dim {x.shape[0]} not divisible by mesh axis '{axis}' size {n}"
            )
        rows = local_batch_size(x.shape[0])
        return global_batch_put(np.asarray(x[pi * rows : (pi + 1) * rows]), s)

    return jax.tree.map(put, batch)


def partition_by_rules(rules: Sequence[tuple[str, P]], params: Pytree) -> Pytree:
    """Pytree of PartitionSpecs chosen by regex match on the leaf path.

    Scalars and unmatched leaves are replicated (``P()``).  Thin alias
    over the declarative rules engine's matcher
    (:func:`~.parallel.rules.match_partition_rules` — ONE matching
    implementation; pass ``mesh=``/``strict=``/``report=`` there for
    validation, ShardLargest values and fallback reporting).
    """
    from .parallel.rules import match_partition_rules

    return match_partition_rules(list(rules), params)


def make_shardings(spec_tree: Pytree, mesh: Mesh) -> Pytree:
    """Convert a tree of PartitionSpecs into NamedShardings on ``mesh``."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )
