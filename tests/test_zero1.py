"""ZeRO-1 weight-update sharding invariants, on the 8-device mesh.

The whole point of ZeRO-1 (arXiv:2004.13336) is that it changes WHERE
the optimizer update runs, never WHAT it computes: reduce-scatter the
gradients, update slice 1/N per device, all-gather the params.  So the
acceptance bar is step-for-step parity with plain DP — both the GSPMD
variant and the explicit-collectives shard_map variant, for adam and
momentum, over multiple steps — plus the memory claim asserted directly:
each device holds ~1/8 of the optimizer state (``addressable_shards``
accounting), and padding of non-divisible leaves round-trips exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu import optim, sharding
from fluxdistributed_tpu.models import MLP, SimpleCNN
from fluxdistributed_tpu.ops import logitcrossentropy
from fluxdistributed_tpu.parallel import (
    TrainState,
    make_train_step,
    make_train_step_zero1,
    make_train_step_zero1_shardmap,
    zero1_state,
)
from fluxdistributed_tpu.parallel import zero1 as zero1_lib
from fluxdistributed_tpu.parallel.dp import flax_loss_fn

BATCH = 32
NCLASS = 10
STEPS = 5


@pytest.fixture(scope="module")
def setup():
    import fluxdistributed_tpu.mesh as mesh_lib

    mesh = mesh_lib.data_mesh(8)
    # odd feature sizes: flattened leaves NOT divisible by 8 exercise the
    # pad-to-multiple path on every layer
    model = MLP(features=(13, NCLASS))
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH, 6, 6, 3), jnp.float32)
    y = jax.nn.one_hot(
        jax.random.randint(jax.random.PRNGKey(2), (BATCH,), 0, NCLASS), NCLASS
    )
    params = model.init(jax.random.PRNGKey(0), x[:2], train=True)["params"]
    loss_fn = flax_loss_fn(model, logitcrossentropy, has_aux_state=False)
    return mesh, params, loss_fn, {"image": x, "label": y}


def _run_dp(loss_fn, opt, mesh, params, batch, steps=STEPS):
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(sharding.replicate(params, mesh), opt)
    b = sharding.shard_batch(batch, mesh)
    losses = []
    for _ in range(steps):
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    return state, losses


@pytest.mark.parametrize("opt_name", ["adam", "momentum"])
def test_gspmd_parity_with_dp(setup, opt_name):
    """zero1 GSPMD params match plain DP after STEPS optimizer steps."""
    mesh, params, loss_fn, batch = setup
    opt = optim.adam(1e-2) if opt_name == "adam" else optim.momentum(0.05, 0.9)
    ref_state, ref_losses = _run_dp(loss_fn, opt, mesh, params, batch)

    state, sh = zero1_state(params, opt, mesh)
    step = make_train_step_zero1(loss_fn, opt, mesh, sh, donate=False)
    b = sharding.shard_batch(batch, mesh)
    losses = []
    for _ in range(STEPS):
        state, m = step(state, b)
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    for a, b_ in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("opt_name", ["adam", "momentum"])
def test_shardmap_parity_with_dp(setup, opt_name):
    """Explicit reduce-scatter/all-gather variant matches plain DP too."""
    mesh, params, loss_fn, batch = setup
    opt = optim.adam(1e-2) if opt_name == "adam" else optim.momentum(0.05, 0.9)
    ref_state, ref_losses = _run_dp(loss_fn, opt, mesh, params, batch)

    state, _ = zero1_state(params, opt, mesh)
    step = make_train_step_zero1_shardmap(loss_fn, opt, mesh, state, donate=False)
    b = sharding.shard_batch(batch, mesh)
    losses = []
    for _ in range(STEPS):
        state, m = step(state, b)
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    for a, b_ in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


def test_optimizer_state_memory_is_sharded_eighth(setup):
    """Per-device optimizer-state bytes ≈ 1/8 of the replicated baseline
    (exactly 1/8 of the PADDED total, asserted via addressable-shard
    accounting), and every device holds the same amount."""
    mesh, params, loss_fn, batch = setup
    opt = optim.adam(1e-2)

    repl = TrainState.create(sharding.replicate(params, mesh), opt)
    base = zero1_lib.per_device_state_bytes(repl.opt_state)

    state, _ = zero1_state(params, opt, mesh)
    got = zero1_lib.per_device_state_bytes(state.opt_state)

    assert set(got) == set(base) and len(got) == 8
    assert len(set(got.values())) == 1, "ZeRO-1 split must be even"
    per_dev = next(iter(got.values()))
    base_per_dev = next(iter(base.values()))
    # padded total / 8: with the MLP's odd leaves the padding overhead is
    # tiny, so per-device lands between exactly-1/8 and 1/7 of replicated
    assert base_per_dev / 8 <= per_dev < base_per_dev / 7, (per_dev, base_per_dev)

    # and params stay replicated (full copy per device) — ZeRO-1, not -3
    p_leaf = jax.tree.leaves(state.params)[0]
    assert p_leaf.addressable_shards[0].data.shape == p_leaf.shape


def test_padding_roundtrip_non_divisible_leaves():
    """_flatten_tree pads to a multiple of N; _unflatten_like restores
    the exact original values and shapes; pad entries stay zero through
    an optimizer update with zero grads."""
    tree = {
        "a": jnp.arange(13.0),            # 13 -> pad 3
        "b": jnp.arange(12.0).reshape(3, 4),  # 12 -> pad 4
        "c": jnp.ones((8,)),              # already divisible
        "frozen": None,
    }
    flat = zero1_lib._flatten_tree(tree, 8)
    assert flat["a"].shape == (16,) and flat["b"].shape == (16,)
    assert flat["c"].shape == (8,) and flat["frozen"] is None
    np.testing.assert_array_equal(np.asarray(flat["a"][13:]), 0.0)
    back = zero1_lib._unflatten_like(flat, tree)
    for k in ("a", "b", "c"):
        np.testing.assert_array_equal(np.asarray(back[k]), np.asarray(tree[k]))
        assert back[k].shape == tree[k].shape

    # momentum on flat leaves: zero grads leave the padded tail at zero
    opt = optim.momentum(0.1, 0.9)
    st = opt.init(flat)
    newp, newst = opt.apply(flat, jax.tree.map(jnp.zeros_like, flat), st, 0)
    np.testing.assert_array_equal(np.asarray(newp["a"]), np.asarray(flat["a"]))
    np.testing.assert_array_equal(np.asarray(newst["a"]), 0.0)


def test_checkpoint_roundtrip_sharded_opt_state(setup, tmp_path):
    """Save a ZeRO-1 state (sharded flat optimizer leaves), restore onto
    a freshly prepared task, and keep training: restored state equals the
    saved one leaf-for-leaf and restores SHARDED (no gather on load)."""
    from fluxdistributed_tpu.train import load_checkpoint, save_checkpoint

    mesh, params, loss_fn, batch = setup
    opt = optim.adam(1e-2)
    state, sh = zero1_state(params, opt, mesh)
    step = make_train_step_zero1(loss_fn, opt, mesh, sh, donate=False)
    b = sharding.shard_batch(batch, mesh)
    for _ in range(3):
        state, _ = step(state, b)
    save_checkpoint(state, str(tmp_path), 3)

    # fresh task (as a resume would build it), then restore onto it
    fresh, _ = zero1_state(params, opt, mesh)
    restored = load_checkpoint(str(tmp_path), fresh, mesh=mesh)
    assert int(restored.step) == 3
    for a, b_ in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
    # sharding preserved: each device holds a 1/8 slice, not a full copy
    leaf = jax.tree.leaves(restored.opt_state)[0]
    assert leaf.addressable_shards[0].data.shape[0] == leaf.shape[0] // 8

    # training continues from the restored state and stays in lockstep
    # with the uninterrupted run
    cont, _ = step(restored, b)
    ref, _ = step(state, b)
    for a, b_ in zip(jax.tree.leaves(ref.params), jax.tree.leaves(cont.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-6, atol=1e-7)


def test_trainer_wiring_and_model_state(tmp_path):
    """prepare_training(spmd='dp', zero1=True) runs end-to-end (BatchNorm
    model state included) and matches the zero1=False trainer path."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.logging import NullLogger

    mesh = mesh_lib.data_mesh(8)
    ds = SyntheticDataset(nsamples=64, nclasses=NCLASS, shape=(8, 8, 3))

    def make(zero1):
        task = prepare_training(
            SimpleCNN(num_classes=NCLASS), ds, optim.momentum(0.05, 0.9),
            mesh=mesh, batch_size=16, cycles=3, seed=7, spmd="dp", zero1=zero1,
        )
        train(task, print_every=0, eval_every=0, logger=NullLogger())
        return task

    t_ref, t_z1 = make(False), make(True)
    assert int(t_z1.state.step) == 3
    for a, b in zip(
        jax.tree.leaves(t_ref.state.params), jax.tree.leaves(t_z1.state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_zero1_rejects_non_dp_modes():
    from fluxdistributed_tpu.train import prepare_training

    with pytest.raises(ValueError, match="zero1"):
        prepare_training(
            SimpleCNN(num_classes=2), None, optim.adam(1e-3),
            spmd="fsdp", zero1=True,
        )


def test_ema_shadow_roundtrip(setup):
    """with_ema under ZeRO-1: the shadow trains flat-sharded;
    zero1_ema_params restores model-shaped EMA params usable for eval."""
    mesh, params, loss_fn, batch = setup
    opt = optim.with_ema(optim.adam(1e-2), decay=0.9)
    state, sh = zero1_state(params, opt, mesh)
    step = make_train_step_zero1(loss_fn, opt, mesh, sh, donate=False)
    b = sharding.shard_batch(batch, mesh)
    for _ in range(3):
        state, _ = step(state, b)
    ema = zero1_lib.zero1_ema_params(state)
    for p, e in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(ema)
    ):
        assert p.shape == e.shape
        # warmup-corrected decay: after 3 steps the shadow tracks the
        # live params closely but is not identical
        assert not np.array_equal(np.asarray(p), np.asarray(e))
    # the shadow drives a forward pass at model shapes
    loss, _ = loss_fn(ema, {}, batch, False)
    assert np.isfinite(float(loss))


def test_shardmap_variant_rejects_norm_based_rules(setup):
    """LARS / global-norm clipping need cross-slice reductions the
    slice-local shard_map update cannot do — actionable error."""
    mesh, params, loss_fn, batch = setup
    state, _ = zero1_state(params, optim.lars(0.1), mesh)
    with pytest.raises(ValueError, match="GSPMD"):
        make_train_step_zero1_shardmap(loss_fn, optim.lars(0.1), mesh, state)


def test_gspmd_composes_with_accum_and_device_loop(setup):
    """accum_steps and steps_per_call ride the zero1 step unchanged:
    2 microbatch-accumulated steps x scan-2 == 2 plain zero1 steps on the
    equivalent batches (mean-loss semantics)."""
    mesh, params, loss_fn, batch = setup
    opt = optim.momentum(0.05, 0.9)
    b = sharding.shard_batch(batch, mesh)

    state, sh = zero1_state(params, opt, mesh)
    plain = make_train_step_zero1(loss_fn, opt, mesh, sh, donate=False)
    s_ref = state
    for _ in range(2):
        s_ref, _ = plain(s_ref, b)

    # accum: same global batch split into 2 microbatches
    accum = make_train_step_zero1(
        loss_fn, opt, mesh, sh, donate=False, accum_steps=2
    )
    s_acc, _ = accum(state, b)
    s_acc, _ = accum(s_acc, b)
    for a, b_ in zip(jax.tree.leaves(s_ref.params), jax.tree.leaves(s_acc.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-5)

    # device loop: 2 steps per dispatch on the stacked [2, batch, ...] item
    chunked = make_train_step_zero1(
        loss_fn, opt, mesh, sh, donate=False, steps_per_call=2
    )
    stacked = jax.tree.map(lambda x: jnp.stack([x, x]), b)
    s_chunk, m = chunked(state, stacked)
    assert m["loss"].shape == (2,)
    for a, b_ in zip(jax.tree.leaves(s_ref.params), jax.tree.leaves(s_chunk.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-5)
