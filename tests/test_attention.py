"""Attention ops + ViT tests.

Net-new scope (the reference has no attention; SURVEY §5), so the test
model here is internal consistency: every attention implementation —
reference XLA softmax, blockwise/online-softmax, (later) Pallas and ring
— must agree numerically, mirroring how the reference pins its DP
machinery to single-batch gradients (test/single_device.jl:42-62).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu.ops.attention import (
    blockwise_attention,
    dot_product_attention,
)


def _qkv(b=2, t=64, h=4, d=16, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def test_blockwise_matches_reference():
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v)
    blk = blockwise_attention(q, k, v, block_size=16)
    np.testing.assert_allclose(np.asarray(blk), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_blockwise_causal_matches_reference():
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=True)
    blk = blockwise_attention(q, k, v, block_size=16, causal=True)
    np.testing.assert_allclose(np.asarray(blk), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_causal_first_token_ignores_future():
    q, k, v = _qkv(t=8)
    out = dot_product_attention(q, k, v, causal=True)
    # Row 0 may only attend to position 0 → output == v[:, 0].
    np.testing.assert_allclose(
        np.asarray(out[:, 0]), np.asarray(v[:, 0]), rtol=1e-5, atol=1e-5
    )


def test_mask_equivalent_to_causal():
    q, k, v = _qkv(t=16)
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
    a = dot_product_attention(q, k, v, causal=True)
    b = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


def test_blockwise_non_divisible_block_size():
    """Tk not divisible by block_size must pad+mask, not fall back."""
    q, k, v = _qkv(t=50)
    ref = dot_product_attention(q, k, v)
    blk = blockwise_attention(q, k, v, block_size=16)
    np.testing.assert_allclose(np.asarray(blk), np.asarray(ref), rtol=1e-5, atol=1e-5)
    refc = dot_product_attention(q, k, v, causal=True)
    blkc = blockwise_attention(q, k, v, block_size=16, causal=True)
    np.testing.assert_allclose(np.asarray(blkc), np.asarray(refc), rtol=1e-5, atol=1e-5)


def test_fully_masked_row_finalizes_to_zero():
    from fluxdistributed_tpu.ops.attention import (
        attn_block_update,
        attn_finalize,
        attn_init,
    )

    q, k, v = _qkv(t=8)
    mask = jnp.zeros((8, 8), bool)  # nothing may attend
    carry = attn_block_update(attn_init(q), q, k, v, mask=mask)
    out = attn_finalize(carry, q.dtype)
    np.testing.assert_array_equal(np.asarray(out), 0.0)


def test_factory_kwargs_overridable():
    from fluxdistributed_tpu.models import vit_b16, vit_tiny

    assert vit_b16(patch=32).patch == 32
    assert vit_tiny(depth=1).depth == 1


def test_attention_grads_match():
    q, k, v = _qkv(t=32)

    def loss_ref(q, k, v):
        return dot_product_attention(q, k, v, causal=True).sum()

    def loss_blk(q, k, v):
        return blockwise_attention(q, k, v, block_size=8, causal=True).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_blk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


class TestViT:
    @pytest.fixture(scope="class")
    def model_and_vars(self):
        from fluxdistributed_tpu.models import vit_tiny

        model = vit_tiny(num_classes=10, dtype=jnp.float32)
        x = jnp.zeros((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        return model, variables

    def test_forward_shape(self, model_and_vars):
        model, variables = model_and_vars
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)
        assert out.dtype == jnp.float32
        assert np.isfinite(np.asarray(out)).all()

    def test_train_step_decreases_loss(self, model_and_vars):
        from fluxdistributed_tpu import logitcrossentropy, onehot
        from fluxdistributed_tpu import optim

        model, variables = model_and_vars
        x = jax.random.normal(jax.random.PRNGKey(2), (8, 32, 32, 3))
        y = onehot(np.arange(8) % 10, 10)
        opt = optim.adam(1e-3)
        params = variables["params"]
        state = opt.init(params)

        @jax.jit
        def step(params, state, i):
            def lf(p):
                logits = model.apply(
                    {"params": p}, x, train=True,
                    rngs={"dropout": jax.random.PRNGKey(i)},
                )
                return logitcrossentropy(logits, y)

            loss, g = jax.value_and_grad(lf)(params)
            params, state = opt.apply(params, g, state, i)
            return params, state, loss

        params, state, l0 = step(params, state, 0)
        for i in range(1, 10):
            params, state, l = step(params, state, i)
        assert float(l) < float(l0)

    def test_pluggable_attention_changes_nothing(self):
        """ViT with blockwise attention == ViT with reference attention."""
        from functools import partial

        from fluxdistributed_tpu.models import vit_tiny

        x = jax.random.normal(jax.random.PRNGKey(3), (2, 32, 32, 3))
        m_ref = vit_tiny(num_classes=10, dtype=jnp.float32)
        variables = m_ref.init(jax.random.PRNGKey(0), x, train=False)
        m_blk = vit_tiny(
            num_classes=10, dtype=jnp.float32,
            attn_fn=partial(blockwise_attention, block_size=16),
        )
        a = m_ref.apply(variables, x, train=False)
        b = m_blk.apply(variables, x, train=False)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)
