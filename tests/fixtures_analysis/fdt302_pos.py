"""FDT302 positive: scrape path takes registry-lock then
scheduler-lock; finish path takes scheduler-lock then registry-lock —
two threads on opposite paths deadlock."""
import threading


class ToyRegistry:
    def __init__(self, sched=None):
        self._lock = threading.Lock()
        self._sched = sched

    def render_exposition(self):
        with self._lock:
            # registry-lock held -> acquires scheduler-lock
            return self._sched.scrape_queue_depth()


class ToyScheduler:
    def __init__(self, registry):
        self._lock = threading.Lock()
        self._registry = registry

    def scrape_queue_depth(self):
        with self._lock:
            return 0

    def finish_request(self):
        with self._lock:
            # scheduler-lock held -> acquires registry-lock: the cycle
            self._registry.render_exposition()
