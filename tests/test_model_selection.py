"""Model-selection trainer (the reference's legacy src/test.jl path).

Invariants: replicas train independently (they diverge between
selections), selection broadcasts the min-val-loss replica to all
(replicas identical right after a cycle), and the loop learns on a
separable task.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu import mesh as mesh_lib, optim, tree as tree_lib
from fluxdistributed_tpu.data import SyntheticDataset
from fluxdistributed_tpu.models import MLP
from fluxdistributed_tpu.ops import onehot
from fluxdistributed_tpu.train.logging import NullLogger
from fluxdistributed_tpu.train.model_selection import (
    prepare_model_selection,
    train_model_selection,
)


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.data_mesh(8)


def _val_batch(ds, n=32, seed=1):
    imgs, labels = ds.batch(np.random.default_rng(seed), n)
    return {"image": jnp.asarray(imgs), "label": onehot(jnp.asarray(labels), ds.nclasses)}


def test_replicas_independent_then_identical_after_selection(mesh):
    ds = SyntheticDataset(nsamples=64, nclasses=4, shape=(8, 8, 3))
    task = prepare_model_selection(
        MLP(features=(16, 4)), optim.momentum(0.05, 0.9),
        mesh=mesh, input_shape=(8, 8, 3),
    )
    # different init per replica → stacked kernels differ across axis 0
    # (biases are zero-init for every replica, so compare a weight leaf)
    kernel = np.asarray(tree_lib.getfirst(task.params, "kernel"))
    assert not np.allclose(kernel[0], kernel[1])

    _, history = train_model_selection(
        task, ds, _val_batch(ds), cycles=1, steps_per_cycle=2,
        batch_size_per_replica=4, logger=NullLogger(),
    )
    # after selection every replica holds the same (best) weights
    for leaf in jax.tree.leaves(task.params):
        arr = np.asarray(leaf)
        for i in range(1, arr.shape[0]):
            np.testing.assert_array_equal(arr[i], arr[0])
    assert len(history) == 1 and history[0].shape == (8,)


def test_selection_learns_separable_task(mesh):
    ds = SyntheticDataset(nsamples=256, nclasses=2, shape=(8, 8, 3), noise=0.1)
    task = prepare_model_selection(
        MLP(features=(32, 2)),
        optim.momentum(optim.step_decay(0.1, 0.2, every=10), 0.9),  # LR/5 every 10
        mesh=mesh, input_shape=(8, 8, 3),
    )
    val = _val_batch(ds, n=64)
    _, history = train_model_selection(
        task, ds, val, cycles=8, steps_per_cycle=4,
        batch_size_per_replica=8, logger=NullLogger(),
    )
    first, last = history[0].min(), history[-1].min()
    assert last < first * 0.7, (first, last)


def test_best_replica_is_argmin(mesh):
    """The broadcast replica must be the argmin-val-loss one."""
    ds = SyntheticDataset(nsamples=64, nclasses=4, shape=(8, 8, 3))
    task = prepare_model_selection(
        MLP(features=(16, 4)), optim.momentum(0.05, 0.9),
        mesh=mesh, input_shape=(8, 8, 3),
    )
    val = _val_batch(ds)
    params_before = jax.tree.map(np.asarray, tree_lib.to_host(task.params))
    new_params, _, _, losses = task.select_fn(
        task.params, task.opt_state, task.model_state, val
    )
    best = int(np.argmin(np.asarray(losses)))
    leaf_new = np.asarray(jax.tree.leaves(new_params)[0])
    leaf_old = jax.tree.leaves(params_before)[0]
    np.testing.assert_array_equal(leaf_new[0], leaf_old[best])
