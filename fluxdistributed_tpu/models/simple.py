"""Small models for tests and smoke runs.

The reference's integration tests build a tiny ``Conv → flatten → Dense``
chain (test/single_device.jl:115-120) rather than a full ResNet; these
are the analogs, used by the invariant tests and CPU fake-device runs.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["SimpleCNN", "MLP"]


class SimpleCNN(nn.Module):
    """Conv(3x3) → relu → Conv(3x3) → relu → global-avg-pool → Dense."""

    num_classes: int = 10
    features: int = 16
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = jnp.asarray(x, self.dtype)
        x = nn.Conv(self.features, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(self.features * 2, (3, 3), (2, 2), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


class MLP(nn.Module):
    features: Sequence[int] = (32, 10)

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        for i, f in enumerate(self.features):
            x = nn.Dense(f)(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x
