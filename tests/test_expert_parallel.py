"""Expert parallelism: sharded MoE == single-device golden model.

The golden model re-implements the identical routing math (same
``router_dispatch``) with a dense loop over experts on one device; the
sharded version must match bit-for-tolerance, including dropped tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu import mesh as mesh_lib, optim
from fluxdistributed_tpu.parallel.ep import (
    moe_apply,
    router_dispatch,
    stack_expert_params,
)

E = 4  # experts = devices on the expert axis
D = 8
T = 32  # global tokens (T/E per shard)


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.make_mesh({"expert": E})


def expert_fn(params, x):
    return jax.nn.gelu(x @ params["w1"]) @ params["w2"]


def _expert_params(key):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (D, 2 * D), jnp.float32) * 0.3,
        "w2": jax.random.normal(k2, (2 * D, D), jnp.float32) * 0.3,
    }


@pytest.fixture(scope="module")
def setup(mesh):
    keys = jax.random.split(jax.random.PRNGKey(0), E)
    per_expert = [_expert_params(k) for k in keys]
    router_w = jax.random.normal(jax.random.PRNGKey(1), (D, E), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(2), (T, D), jnp.float32)
    return per_expert, router_w, x


def golden_moe(per_expert, router_w, x_shard, capacity, k=1):
    """Dense single-shard reference with the same routing math."""
    logits = x_shard @ router_w
    dispatch, combine, aux = router_dispatch(logits, capacity, k=k)
    expert_in = jnp.einsum("td,tec->ecd", x_shard, dispatch)  # (E, C, D)
    y = jnp.stack([expert_fn(p, expert_in[e]) for e, p in enumerate(per_expert)])
    out = jnp.einsum("ecd,tec->td", y, combine)
    return out, aux


def test_moe_matches_golden_model(setup, mesh):
    per_expert, router_w, x = setup
    import math

    t_shard = T // E
    cap = max(1, math.ceil(t_shard / E * 1.25))
    fn = moe_apply(expert_fn, mesh, capacity_factor=1.25)
    stacked = stack_expert_params(per_expert, mesh)
    got, aux = fn(stacked, router_w, x)
    got = np.asarray(got)

    # golden: routing happens per shard (tokens sharded on the axis)
    outs, auxes = [], []
    for s in range(E):
        o, a = golden_moe(per_expert, router_w, x[s * t_shard : (s + 1) * t_shard], cap)
        outs.append(np.asarray(o))
        auxes.append(float(a))
    want = np.concatenate(outs)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), np.mean(auxes), rtol=1e-5)


def test_moe_multiple_experts_per_device(mesh):
    """E = 2× devices: each device hosts two experts, still matches the
    dense golden model."""
    import math

    e_total = 2 * E
    keys = jax.random.split(jax.random.PRNGKey(7), e_total)
    per_expert = [_expert_params(k) for k in keys]
    router_w = jax.random.normal(jax.random.PRNGKey(8), (D, e_total), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(9), (T, D), jnp.float32)

    t_shard = T // E
    cap = max(1, math.ceil(t_shard / e_total * 2.0))
    fn = moe_apply(expert_fn, mesh, capacity_factor=2.0)
    stacked = stack_expert_params(per_expert, mesh)
    got, aux = fn(stacked, router_w, x)
    got = np.asarray(got)

    outs, auxes = [], []
    for s in range(E):
        o, a = golden_moe(per_expert, router_w, x[s * t_shard : (s + 1) * t_shard], cap)
        outs.append(np.asarray(o))
        auxes.append(float(a))
    np.testing.assert_allclose(got, np.concatenate(outs), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), np.mean(auxes), rtol=1e-5)


def test_moe_top2_matches_golden(mesh, setup):
    """GShard-style top-2 routing matches the dense golden model."""
    import math

    per_expert, router_w, x = setup
    t_shard = T // E
    cap = max(1, math.ceil(t_shard / E * 1.25 * 2))
    fn = moe_apply(expert_fn, mesh, capacity_factor=1.25, top_k=2)
    stacked = stack_expert_params(per_expert, mesh)
    got, aux = fn(stacked, router_w, x)
    got = np.asarray(got)

    outs = []
    for s in range(E):
        o, _ = golden_moe(
            per_expert, router_w, x[s * t_shard : (s + 1) * t_shard], cap, k=2
        )
        outs.append(np.asarray(o))
    np.testing.assert_allclose(got, np.concatenate(outs), rtol=1e-5, atol=1e-5)


def test_top2_gates_normalized():
    """Top-2 combine weights for a kept token sum to ~1."""
    logits = jnp.asarray(np.random.default_rng(0).normal(0, 1, (16, 4)), jnp.float32)
    dispatch, combine, _ = router_dispatch(logits, capacity=16, k=2)
    d = np.asarray(dispatch)
    c = np.asarray(combine)
    assert np.allclose(d.sum(axis=(1, 2)), 2.0)  # both choices kept
    np.testing.assert_allclose(c.sum(axis=(1, 2)), 1.0, rtol=1e-5)


def test_capacity_drops_overflow_tokens(mesh, setup):
    per_expert, _, _ = setup
    # router that sends EVERY token to expert 0 → only `capacity` survive
    router_w = jnp.zeros((D, E)).at[:, 0].set(0.0)  # uniform → argmax picks 0
    x = jnp.ones((T, D), jnp.float32)
    fn = moe_apply(expert_fn, mesh, capacity=1)
    stacked = stack_expert_params(per_expert, mesh)
    out, _ = fn(stacked, router_w, x)
    out = np.asarray(out)
    t_shard = T // E
    for s in range(E):
        shard = out[s * t_shard : (s + 1) * t_shard]
        assert np.abs(shard[0]).max() > 0  # first routed token computed
        np.testing.assert_array_equal(shard[1:], 0)  # overflow dropped


def test_router_dispatch_bf16_long_queue():
    """Queue positions must stay exact for bf16 logits past 256 tokens —
    a bf16 cumsum saturates at 256 and collapses later positions."""
    t = 400
    logits = jnp.zeros((t, 2), jnp.bfloat16).at[:, 0].set(1.0)  # all → expert 0
    dispatch, _, _ = router_dispatch(logits, capacity=t)
    d = np.asarray(dispatch, np.float32)
    # every token keeps its own slot: one-hot rows, each slot used once
    assert d[:, 0].sum() == t
    np.testing.assert_array_equal(d[:, 0].sum(axis=0), np.ones(t))


def test_moe_trains_end_to_end(mesh):
    """Experts + router train jointly through the sharded program."""
    rng = np.random.default_rng(0)
    y_cls = rng.integers(0, 2, T)
    x = rng.normal(0, 0.3, (T, D)).astype(np.float32)
    x[:, 0] += y_cls * 2.0
    target = np.zeros((T, D), np.float32)
    target[:, 1] = y_cls  # predict class in feature 1

    keys = jax.random.split(jax.random.PRNGKey(5), E)
    stacked = stack_expert_params([_expert_params(k) for k in keys], mesh)
    router_w = jax.random.normal(jax.random.PRNGKey(6), (D, E)) * 0.1
    fn = moe_apply(expert_fn, mesh, capacity_factor=2.0)
    opt = optim.adam(1e-2)
    params = {"experts": stacked, "router": router_w}
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, step_i):
        def lossf(p):
            out, aux = fn(p["experts"], p["router"], jnp.asarray(x))
            return jnp.mean((out - target) ** 2) + 0.01 * aux

        l, g = jax.value_and_grad(lossf)(params)
        params, opt_state = opt.apply(params, g, opt_state, step_i)
        return params, opt_state, l

    losses = []
    for i in range(100):
        params, opt_state, l = step(params, opt_state, i)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, losses[::25]


def test_expert_choice_matches_golden(mesh, setup):
    """Expert-choice routing matches a dense single-shard reference."""
    from fluxdistributed_tpu.parallel.ep import router_dispatch_expert_choice

    per_expert, router_w, x = setup
    t_shard = T // E
    cap = 3  # each expert takes its top-3 tokens per shard
    fn = moe_apply(expert_fn, mesh, capacity=cap, routing="expert_choice")
    stacked = stack_expert_params(per_expert, mesh)
    got, aux = fn(stacked, router_w, x)
    got = np.asarray(got)
    assert float(aux) == 0.0  # perfectly balanced by construction

    outs = []
    for s in range(E):
        xs = x[s * t_shard : (s + 1) * t_shard]
        logits = xs @ router_w
        dispatch, combine, _ = router_dispatch_expert_choice(logits, cap)
        expert_in = jnp.einsum("td,tec->ecd", xs, dispatch)
        y = jnp.stack([expert_fn(p, expert_in[e]) for e, p in enumerate(per_expert)])
        outs.append(np.asarray(jnp.einsum("ecd,tec->td", y, combine)))
    np.testing.assert_allclose(got, np.concatenate(outs), rtol=1e-5, atol=1e-5)


def test_expert_choice_every_expert_full():
    """Every expert processes exactly `capacity` token slots."""
    from fluxdistributed_tpu.parallel.ep import router_dispatch_expert_choice

    logits = jnp.asarray(np.random.default_rng(0).normal(0, 1, (32, 4)), jnp.float32)
    dispatch, _, _ = router_dispatch_expert_choice(logits, capacity=5)
    d = np.asarray(dispatch)  # (T, E, C)
    np.testing.assert_array_equal(d.sum(axis=(0, 2)), np.full(4, 5.0))
    # each (expert, slot) holds exactly one token
    np.testing.assert_array_equal(d.sum(axis=0), np.ones((4, 5)))


def test_expert_choice_validations(mesh):
    from fluxdistributed_tpu.parallel.ep import router_dispatch_expert_choice

    with pytest.raises(ValueError, match="cannot exceed"):
        router_dispatch_expert_choice(jnp.zeros((4, 2)), capacity=5)
    with pytest.raises(ValueError, match="token-choice"):
        moe_apply(expert_fn, mesh, routing="expert_choice", top_k=2)
    with pytest.raises(ValueError, match="unknown routing"):
        moe_apply(expert_fn, mesh, routing="nope")


def test_expert_choice_multiple_experts_per_device(mesh):
    """Expert-choice with E = 2x devices (LOC=2) matches the golden model
    — guards the local-expert block ordering through the all_to_all."""
    from fluxdistributed_tpu.parallel.ep import router_dispatch_expert_choice

    e_total = 2 * E
    keys = jax.random.split(jax.random.PRNGKey(10), e_total)
    per_expert = [_expert_params(k) for k in keys]
    router_w = jax.random.normal(jax.random.PRNGKey(11), (D, e_total), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(12), (T, D), jnp.float32)

    t_shard = T // E
    cap = 2
    fn = moe_apply(expert_fn, mesh, capacity=cap, routing="expert_choice")
    stacked = stack_expert_params(per_expert, mesh)
    got, _ = fn(stacked, router_w, x)
    got = np.asarray(got)

    outs = []
    for s in range(E):
        xs = x[s * t_shard : (s + 1) * t_shard]
        logits = xs @ router_w
        dispatch, combine, _ = router_dispatch_expert_choice(logits, cap)
        expert_in = jnp.einsum("td,tec->ecd", xs, dispatch)
        y = jnp.stack([expert_fn(p, expert_in[e]) for e, p in enumerate(per_expert)])
        outs.append(np.asarray(jnp.einsum("ecd,tec->td", y, combine)))
    np.testing.assert_allclose(got, np.concatenate(outs), rtol=1e-5, atol=1e-5)
