#!/usr/bin/env python
"""Attention-core microbenchmark: Pallas flash vs XLA blockwise vs dense.

The framework's hand-written hot-op (ops/pallas_attention.py) exists to
beat the dense core's HBM behavior at long T; this measures whether it
does on real hardware — per-core ms and achieved TFLOP/s for forward and
forward+backward at growing sequence lengths, causal, bf16.

    python benchmarks/attention_bench.py                    # TPU
    python benchmarks/attention_bench.py --platform cpu \
        --seqlens 128 --batch 1 --heads 2 --dim 32          # smoke

Attention FLOPs ≈ 4·B·H·T²·D forward (q·kᵀ + p·v), halved when causal;
backward ≈ 2.5× forward.  Run under `timeout`, never kill a TPU client.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)

# the shared pure-function timing protocol (3-step post-compile warmup),
# so attention rows are measured like every other hw_session row
from train_step_segments import timeit  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--seqlens", default="1024,2048,4096")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--block", type=int, default=128)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--window", type=int, default=None,
                    help="add a windowed pallas-flash row (block-skip "
                         "FLOPs saving at long T)")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from fluxdistributed_tpu.ops.attention import (
        blockwise_attention, dot_product_attention,
    )
    from fluxdistributed_tpu.ops.pallas_attention import flash_attention

    B, H, D = args.batch, args.heads, args.dim
    blk = args.block
    cores = [
        ("dense", jax.jit(lambda q, k, v: dot_product_attention(q, k, v, causal=True))),
        ("blockwise-xla", jax.jit(
            lambda q, k, v: blockwise_attention(q, k, v, block_size=blk, causal=True))),
        ("pallas-flash", jax.jit(
            lambda q, k, v: flash_attention(q, k, v, True, blk, blk))),
    ]
    if args.window is not None:
        w = args.window
        if w < 1:
            raise SystemExit(f"--window must be >= 1, got {w}")
        cores.append((f"pallas-flash-w{w}", jax.jit(
            lambda q, k, v: flash_attention(q, k, v, True, blk, blk, w))))
    grads = {
        name: jax.jit(jax.grad(lambda q, k, v, f=fn: jnp.sum(f(q, k, v).astype(jnp.float32)),
                               argnums=(0, 1, 2)))
        for name, fn in cores
    }

    rows = []
    for t in [int(s) for s in args.seqlens.split(",")]:
        rng = np.random.default_rng(0)
        q, k, v = (
            jnp.asarray(rng.normal(0, 1, (B, t, H, D)), jnp.bfloat16)
            for _ in range(3)
        )
        fwd_flops = 4 * B * H * t * t * D / 2  # causal halves the score work
        if args.window is not None:
            # the windowed kernel's USEFUL work is the band, not T^2/2:
            # sum_q min(q+1, W) attended keys (otherwise its TFLOP/s
            # column would overstate by ~T/W and could exceed chip peak)
            w = min(args.window, t)
            attended = w * (w + 1) // 2 + max(t - w, 0) * w
            fwd_flops_windowed = 4 * B * H * D * attended
        for name, fn in cores:
            if name == "dense" and t > 8192:
                continue  # T^2 scores in HBM; keep the sweep bounded
            dt = timeit(fn, q, k, v, n=args.iters)
            dtg = timeit(grads[name], q, k, v, n=max(5, args.iters // 2))
            fl = fwd_flops_windowed if name.startswith("pallas-flash-w") else fwd_flops
            rows.append({
                "core": name, "T": t,
                "fwd_ms": round(dt * 1e3, 3),
                "fwd_tflops": round(fl / dt / 1e12, 2),
                "fwdbwd_ms": round(dtg * 1e3, 3),
            })
            print(json.dumps(rows[-1]), flush=True)

    print(json.dumps({
        "metric": "attention-core microbench (causal, bf16)",
        "config": {"B": B, "H": H, "D": D, "block": blk},
        "platform": jax.devices()[0].platform,
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
