#!/usr/bin/env python
"""Transformer-LM train-step throughput: tokens/sec/chip.

The LM-side companion of the headline ResNet bench (bench.py — the
reference publishes no numbers at all, SURVEY §6, so these define the
baseline).  Measures the compiled DP train step (fwd + bwd + implicit
grad all-reduce + adam update, bf16 compute) on synthetic token batches
with the shared timing protocol (``bench.time_compiled_step``), so rows
are comparable to the ResNet numbers.

    python benchmarks/lm_bench.py                       # lm_small, T=1024
    python benchmarks/lm_bench.py --model lm_medium --seqlen 2048 --remat
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="lm_small",
                    choices=["lm_tiny", "lm_small", "lm_medium"])
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--seqlen", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=0,
                    help="global batch (sequences); 0 = 8/chip on TPU, 2/device on CPU")
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--attn", default="dense",
                    choices=["dense", "blockwise", "flash"],
                    help="attention core: XLA dense, XLA blockwise, or the "
                         "Pallas flash kernel (fwd AND bwd)")
    ap.add_argument("--attn-block", type=int, default=128)
    ap.add_argument("--kv-heads", type=int, default=None,
                    help="grouped-query attention: number of KV heads")
    ap.add_argument("--window", type=int, default=None,
                    help="sliding-window attention (newest WINDOW keys)")
    ap.add_argument("--sinks", type=int, default=0,
                    help="StreamingLLM attention sinks (requires --window)")
    ap.add_argument("--norm", default="layernorm",
                    choices=["layernorm", "rmsnorm"])
    ap.add_argument("--mlp", default="gelu", choices=["gelu", "swiglu"])
    ap.add_argument("--seconds", type=float, default=2.0)
    ap.add_argument("--platform", default=None, help="force platform (e.g. cpu)")
    args = ap.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import numpy as np

    import bench
    from fluxdistributed_tpu import mesh as mesh_lib, models, optim, sharding
    from fluxdistributed_tpu.parallel import TrainState, make_train_step

    nchips = jax.device_count()
    platform = jax.devices()[0].platform
    batch = args.batch or (8 if platform == "tpu" else 2) * nchips

    mesh = mesh_lib.data_mesh()
    from fluxdistributed_tpu.ops import attention_core

    model = getattr(models, args.model)(
        vocab=args.vocab, remat=args.remat,
        attn_fn=attention_core(args.attn, args.attn_block,
                               window=args.window, sinks=args.sinks),
        num_kv_heads=args.kv_heads, window=args.window, sinks=args.sinks,
        norm=args.norm, mlp=args.mlp)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, args.vocab, (batch, args.seqlen)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:1], train=False)["params"]
    nparams = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))

    opt = optim.adam(1e-3)
    state = TrainState.create(sharding.replicate(params, mesh), opt)
    step = make_train_step(models.lm_loss_fn(model), opt, mesh, donate=True)
    b = sharding.shard_batch({"tokens": toks}, mesh)

    # exact FLOPs from XLA cost analysis (before timing — donation kills
    # the state buffers) feeds the hardware-normalized MFU figure
    fl = bench.step_flops(step, state, b)
    dt, iters = bench.time_compiled_step(step, state, b, target_seconds=args.seconds)
    tok_s_chip = batch * args.seqlen / dt / nchips
    # decoder train step ~= 6*N FLOPs/token (fwd 2N + bwd 4N), +1 fwd if remat
    flops_per_tok = (8 if args.remat else 6) * nparams
    print(json.dumps({
        "metric": f"{args.model} train-step throughput ({platform}, B={batch}, "
                  f"T={args.seqlen}, vocab {args.vocab}"
                  f"{', remat' if args.remat else ''})",
        "value": round(tok_s_chip, 1),
        "unit": "tokens/sec/chip",
        "mfu_pct": bench.mfu_pct(fl, dt, nchips),
        "params_millions": round(nparams / 1e6, 1),
        "approx_model_tflops_per_chip": round(tok_s_chip * flops_per_tok / 1e12, 2),
        "step_ms": round(dt * 1e3, 2),
        "iters": iters,
    }))


if __name__ == "__main__":
    main()
