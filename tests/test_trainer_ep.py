"""MoE expert parallelism as a first-class trainer mode.

``prepare_training(spmd="ep")`` shards the MoE LM's expert-stacked
leaves over the mesh's ``expert`` axis while tokens ride the ``data``
axis; the model's mesh-bound ``moe_fn`` performs the all_to_all
dispatch inside the generic jit step.  Rides the full trainer surface.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu import mesh as mesh_lib, optim
from fluxdistributed_tpu.data import SyntheticTextDataset
from fluxdistributed_tpu.models import moe_expert_fn
from fluxdistributed_tpu.models.transformer_lm import TransformerLM
from fluxdistributed_tpu.parallel.ep import moe_apply
from fluxdistributed_tpu.train import prepare_training

VOCAB = 32


@pytest.fixture(scope="module")
def ep_mesh():
    return mesh_lib.make_mesh({"data": 2, "expert": 4})


def _moe_model(mesh, experts=8):
    return TransformerLM(
        vocab=VOCAB, dim=32, depth=2, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
        moe_every=2, num_experts=experts,
        moe_fn=moe_apply(moe_expert_fn, mesh, capacity_factor=2.0,
                         batch_axis="data"),
    )


def test_ep_trainer_mode_trains_and_evaluates(ep_mesh):
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=24, peak=0.95)
    task = prepare_training(
        _moe_model(ep_mesh), ds, optim.adam(3e-3),
        mesh=ep_mesh, batch_size=16, cycles=40, spmd="ep",
        val_dataset=ds, val_samples=8,
    )  # default topk: coerced to loss-only for the LM
    # expert-stacked leaves are sharded over the expert axis: each
    # device holds 2 of the 8 experts
    w1 = task.state.params["block1"]["w1"]
    assert w1.shape[0] == 8 and w1.addressable_shards[0].data.shape[0] == 2
    losses = []
    for batch in task.loader:
        task.state, m = task.step_fn(task.state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    loss, metrics = task.eval_fn(task.state, task.val_batch)
    assert np.isfinite(float(loss)) and metrics == {}


def test_ep_mode_rejects_bad_configs(ep_mesh):
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=24)
    dense = TransformerLM(
        vocab=VOCAB, dim=32, depth=2, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
    )
    with pytest.raises(ValueError, match="moe_every > 0"):
        prepare_training(
            dense, ds, optim.adam(1e-3),
            mesh=ep_mesh, batch_size=16, spmd="ep", topk=(),
        )
    with pytest.raises(ValueError, match="expert"):
        prepare_training(
            _moe_model(ep_mesh), ds, optim.adam(1e-3),
            mesh=mesh_lib.data_mesh(8), batch_size=16, spmd="ep", topk=(),
        )
