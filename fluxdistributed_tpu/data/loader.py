"""Prefetching device-resident data loader.

TPU-native replacement for the reference's forked-Flux ``DataLoader(f,
src; buffersize=5)`` — a background task that keeps a channel of
device-resident batches filled ahead of the training loop
(src/ddp_tasks.jl:277-284; the fork is pinned in the Manifest, see
SURVEY §1).  Here: a thread pool assembles host batches (sampling +
one-hot) and ``jax.device_put``s them with the batch sharding so every
step's input is already laid out across the mesh when the train loop
asks for it — host→HBM transfer overlaps compute exactly as the
reference's prefetch loader overlapped H2D copies.

The loader owns the epoch→cycle accounting the reference does in
``prepare_training`` (``cycles = nrow*epochs ÷ ndev ÷ nsamples``,
src/ddp_tasks.jl:256).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import mesh as mesh_lib
from ..ops import onehot

__all__ = ["PrefetchLoader", "batch_to_dict", "model_input"]


def batch_to_dict(out, nclasses=None, one_hot: bool = True) -> dict:
    """Normalize a ``dataset.batch()`` return to the framework batch dict.

    THE single implementation of the three dataset protocols (tuple /
    dict / bare array) — the loader, the trainer's val draw, and init
    shape inference all go through here so the protocols cannot drift.
    """
    if isinstance(out, tuple):
        imgs, labels = out
        y = np.asarray(labels)
        if one_hot:
            if nclasses is None:
                raise ValueError(
                    "one_hot labels need nclasses (dataset lacks .nclasses)"
                )
            y = np.asarray(onehot(y, nclasses))
        return {"image": np.asarray(imgs), "label": y}
    if isinstance(out, dict):
        return {k: np.asarray(v) for k, v in out.items()}
    return {"tokens": np.asarray(out)}


def apply_transform(transform, out):
    """Dispatch a host-side batch hook per the dataset protocol: tuple
    draws unpack to ``transform(imgs, labels)``, dict/bare-array draws
    pass as one argument.  The ONE place the dispatch rule lives —
    PrefetchLoader, prepare_training, and evaluate all route through it
    so training/eval always see the same layout."""
    if transform is None:
        return out
    return transform(*out) if isinstance(out, tuple) else transform(out)


def model_input(out) -> np.ndarray:
    """The array a model's ``init`` should trace from a ``batch()`` draw:
    ``image`` / ``tokens`` by convention, else the dict's first entry."""
    d = batch_to_dict(out, one_hot=False)
    for k in ("image", "tokens"):
        if k in d:
            return d[k]
    return next(iter(d.values()))


class PrefetchLoader:
    """Iterate device-sharded batches with background prefetch.

    The dataset's ``batch(rng, n)`` return decides the batch layout:

    * ``(imgs, labels)`` tuple → ``{"image", "label"}`` (one-hot per
      ``one_hot``) — the image-classification protocol;
    * a dict of arrays → sharded as-is (each leaf's leading dim split);
    * a single array → ``{"tokens": ...}`` — the LM protocol
      (:class:`~fluxdistributed_tpu.data.SyntheticTextDataset`).

    Parameters
    ----------
    dataset: object with ``batch(rng, n)`` as above (``nclasses`` needed
        only for the tuple protocol's one-hot labels)
    mesh: the device mesh; batches are sharded on ``axis``
    batch_size: *global* batch size (reference semantics: per-device batch
        × number of devices; README.md:43's 96/device × N)
    cycles: number of batches to produce; ``None`` derives it from
        ``len(dataset) * epochs // batch_size`` (the reference's
        epoch→cycle conversion, src/ddp_tasks.jl:256)
    buffersize: prefetch depth (reference default 5, src/ddp_tasks.jl:278)
    one_hot: emit one-hot labels (the reference's ``onehotbatch``,
        src/imagenet.jl:47); integer labels otherwise
    transform: optional host-side hook, called per the dataset protocol:
        ``transform(imgs, labels)`` for tuple datasets, ``transform(out)``
        (one argument) for dict / bare-array datasets
    start: first item index to yield (resume cursor).  Batch content is
        a pure function of ``(seed, process, index)``, so a resumed run
        starting at the preempted run's ``next_item`` sees byte-identical
        batches from there on — the loss-parity contract
        (docs/robustness.md)
    retries: transient host-side assembly failures (I/O hiccups in a
        real decode pipeline; injected faults in tests) are retried this
        many times per batch before surfacing to the consumer
    """

    def __init__(
        self,
        dataset,
        mesh: Mesh,
        batch_size: int,
        cycles: Optional[int] = None,
        epochs: int = 1,
        buffersize: int = 5,
        seed: int = 0,
        axis: str = mesh_lib.DATA_AXIS,
        one_hot: bool = True,
        num_threads: int = 2,
        transform: Optional[Callable] = None,
        chunk: int = 1,
        start: int = 0,
        retries: int = 2,
    ):
        from ..sharding import axis_size, batch_entry

        n = axis_size(mesh, axis)
        if batch_size % n:
            raise ValueError(
                f"global batch {batch_size} not divisible by mesh axis '{axis}' size {n}"
            )
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.dataset = dataset
        self.mesh = mesh
        self.batch_size = batch_size
        self.buffersize = buffersize
        self.one_hot = one_hot
        self.transform = transform
        self.seed = seed
        self.num_threads = max(1, num_threads)
        # chunk > 1: the device-loop layout for steps_per_call training —
        # each yielded item stacks `chunk` per-step batches on a NEW
        # leading dim, sharded [K(replicated), batch(data axis), ...].
        # Sub-batch j of item c is bit-identical to step c*chunk+j of an
        # unchunked run (same rng derivation), so chunking never changes
        # what the model sees, only how many dispatches feed it.
        self.chunk = chunk
        if start < 0:
            raise ValueError(f"start must be >= 0, got {start}")
        self.start = start
        self.retries = max(0, retries)
        self.sharding = NamedSharding(mesh, P(batch_entry(axis)))
        self._chunk_sharding = NamedSharding(mesh, P(None, batch_entry(axis)))
        # observability: queue depth + h2d timing land in the process
        # registry so /metrics can answer "is the input pipeline keeping
        # up"; a tracer (set by train() when span tracing is on) adds
        # h2d spans on the worker threads' own timeline rows
        from ..obs import get_registry

        reg = get_registry()
        self.tracer = None
        self._m_depth = reg.gauge(
            "fdtpu_data_prefetch_depth",
            "device-ready batches waiting in the prefetch queue "
            "(0 at read time = the train loop is data-bound)")
        self._m_h2d = reg.histogram(
            "fdtpu_data_h2d_seconds",
            "seconds per batch for host->device transfer (device_put "
            "inside a prefetch worker, overlapped with compute)")
        self._m_assemble = reg.histogram(
            "fdtpu_data_assemble_seconds",
            "seconds per batch for host-side assembly (sampling, "
            "decode, one-hot, transform)")
        self._m_batches = reg.counter(
            "fdtpu_data_batches_total", "batches produced by the loader")
        # Multi-host: each process assembles only its rows of the global
        # batch (the analog of each reference worker sampling its own
        # minibatch, src/sync.jl:135); jax.make_array_from_process_local_data
        # stitches them into one globally-sharded array.
        from ..parallel import multihost

        self._local_batch = multihost.local_batch_size(batch_size)
        if cycles is None:
            if not hasattr(dataset, "__len__"):
                raise ValueError(
                    f"{type(dataset).__name__} has no __len__ (an unbounded "
                    "stream, e.g. a generated token dataset) — pass cycles= "
                    "explicitly instead of deriving it from epochs"
                )
            # derived count: round down to a chunk multiple (a caller
            # never chose this exact number, so don't error on it)
            cycles = max(1, (len(dataset) * epochs) // batch_size)
            cycles = max(self.chunk, cycles // self.chunk * self.chunk)
        if cycles % self.chunk:
            raise ValueError(
                f"cycles ({cycles}) must be a multiple of chunk ({self.chunk})"
            )
        self.cycles = cycles

    # -- host-side batch assembly ------------------------------------
    def _make_batch(self, i: int):
        # Per-batch stream keyed on (seed, process, batch index): batch
        # content is a pure function of the index, so runs with the same
        # seed are bit-reproducible no matter which prefetch thread
        # assembles which batch.  Distinct per process, so hosts sample
        # different rows (the analog of the reference's per-worker
        # sampling, src/sync.jl:135).
        from .. import faults

        faults.fire("loader", index=i)
        rng = np.random.default_rng((self.seed, jax.process_index(), i))
        out = self.dataset.batch(rng, self._local_batch)
        return apply_transform(self.transform, out)

    def _make_item(self, c: int):
        """Host-side assembly of yielded item ``c``: one batch, or a
        ``chunk``-stacked group of consecutive step batches."""
        if self.chunk == 1:
            return self._make_batch(c)
        nclasses = getattr(self.dataset, "nclasses", None)
        ds = [
            batch_to_dict(
                self._make_batch(c * self.chunk + j), nclasses, self.one_hot
            )
            for j in range(self.chunk)
        ]
        return {k: np.stack([d[k] for d in ds]) for k in ds[0]}

    def _put(self, out):
        from ..parallel.multihost import global_batch_put

        if self.chunk > 1:
            # out is already a stacked dict; rows live on dim 1
            return {
                k: global_batch_put(v, self._chunk_sharding, batch_dim=1)
                for k, v in out.items()
            }
        d = batch_to_dict(
            out, getattr(self.dataset, "nclasses", None), self.one_hot
        )
        return {k: global_batch_put(v, self.sharding) for k, v in d.items()}

    # -- iteration ----------------------------------------------------
    def __len__(self) -> int:
        """Number of yielded items (= optimizer steps / chunk)."""
        return self.cycles // self.chunk

    def __iter__(self) -> Iterator[dict]:
        from .. import faults

        if self.start > len(self):
            raise ValueError(
                f"start item {self.start} is past the end of the run "
                f"({len(self)} items) — a stale RESUME manifest?")
        q: queue.Queue = queue.Queue(maxsize=self.buffersize)
        counter = iter(range(self.start, len(self)))
        lock = threading.Lock()
        stop = threading.Event()

        # Backpressure: workers may run at most ``buffersize`` batches
        # ahead of the consumer (the reorder buffer would otherwise grow
        # unboundedly while the consumer waits on one slow index, holding
        # arbitrarily many device-resident batches in HBM).
        ahead = threading.Semaphore(self.buffersize)

        def worker():
            while not stop.is_set():
                if not ahead.acquire(timeout=0.5):
                    continue
                with lock:
                    i = next(counter, None)
                if i is None:
                    ahead.release()
                    break
                try:
                    # device_put from a worker thread: transfer overlaps
                    # the consumer's compute, like the reference's
                    # prefetch tasks
                    t0 = time.perf_counter()
                    # transient assembly failures (real I/O or injected
                    # via the fault plan) cost a short backoff, not the
                    # run; batch content is index-pure so a retry is
                    # bit-identical
                    host = faults.with_retries(
                        lambda: self._make_item(i),
                        tries=self.retries + 1, backoff=0.05,
                        site="loader")
                    t1 = time.perf_counter()
                    self._m_assemble.observe(t1 - t0)
                    tracer = self.tracer
                    if tracer is not None:
                        with tracer.span("h2d", batch=i):
                            dev = self._put(host)
                    else:
                        dev = self._put(host)
                    self._m_h2d.observe(time.perf_counter() - t1)
                    self._m_batches.inc()
                    item = (i, dev, None)
                except Exception as e:  # surface to the consumer, don't die silently
                    item = (i, None, e)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.5)
                        break
                    except queue.Full:
                        continue
                if item[2] is not None:
                    return

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self.num_threads)
        ]
        for t in threads:
            t.start()

        # Deliver strictly in batch-index order (threads may finish out of
        # order): determinism costs only a small reorder buffer.
        pending: dict = {}
        next_idx = self.start
        try:
            while next_idx < len(self):
                while next_idx not in pending:
                    i, batch, err = q.get()
                    if err is not None:
                        raise RuntimeError(
                            "prefetch worker failed while assembling a batch"
                        ) from err
                    pending[i] = batch
                # ready-ahead depth as the consumer sees it: queued items
                # plus out-of-order arrivals already buffered
                self._m_depth.set(q.qsize() + len(pending) - 1)
                yield pending.pop(next_idx)
                next_idx += 1
                ahead.release()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
