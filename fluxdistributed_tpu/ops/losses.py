"""Loss functions.

The reference trains exclusively with ``Flux.Losses.logitcrossentropy``
(README.md:46; the inner loss closure at src/ddp_tasks.jl:28).  Flux's
convention is class-major (classes x batch); here we use the JAX-native
batch-major layout (batch x classes) throughout.

All losses reduce with a *mean over the batch dimension* — under a jitted
program whose batch is sharded over the ``data`` mesh axis, that global
mean is exactly what makes XLA emit the gradient all-reduce that replaces
the reference's hub-reduce (src/ddp_tasks.jl:93-109).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["logitcrossentropy", "crossentropy", "mse"]


def logitcrossentropy(logits, labels, label_smoothing: float = 0.0):
    """Cross entropy on unnormalized logits.

    ``labels`` is one-hot (batch x classes) or integer class ids (batch,).
    Matches ``Flux.logitcrossentropy`` semantics (mean over batch) with an
    optional label-smoothing extension.
    """
    logits = logits.astype(jnp.float32)
    nclasses = logits.shape[-1]
    if labels.ndim == logits.ndim - 1:
        labels = jax.nn.one_hot(labels, nclasses, dtype=jnp.float32)
    else:
        labels = labels.astype(jnp.float32)
    if label_smoothing > 0.0:
        labels = labels * (1.0 - label_smoothing) + label_smoothing / nclasses
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.sum(labels * logp, axis=-1))


def crossentropy(probs, labels, eps: float = 1e-12):
    """Cross entropy on probabilities (post-softmax)."""
    nclasses = probs.shape[-1]
    if labels.ndim == probs.ndim - 1:
        labels = jax.nn.one_hot(labels, nclasses, dtype=jnp.float32)
    return -jnp.mean(jnp.sum(labels * jnp.log(probs + eps), axis=-1))


def mse(pred, target):
    return jnp.mean(jnp.square(pred - target))
