"""Self-healing training guard (train/guard.py) — the acceptance core.

The contract under test: an injected NaN-grad/loss at step k with the
guard enabled finishes the run with final params BIT-IDENTICAL to a
clean run that deterministically skipped the same batch; anomalies that
persist roll back to the last-good checkpoint and replay with the
quarantined span skipped; rollback loops halt with ``retryable=False``.
Injection rides the ``faults`` value sites (``train.loss`` /
``train.grad`` with ``nan``/``inf`` actions) — RNG-free, recompile-free.

Fast tier: policy-engine units (no jax) + in-process trainer runs on
the 8-virtual-device fake mesh, including the rollback × ZeRO-1 ×
elastic-resume interplay.  Slow tier: bin/driver.py subprocess e2e
(--guard quarantine end-to-end, --replay-step, guard-halt rc 65).
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from fluxdistributed_tpu import faults, optim
from fluxdistributed_tpu.data import SyntheticDataset
from fluxdistributed_tpu.mesh import data_mesh
from fluxdistributed_tpu.models import MLP
from fluxdistributed_tpu.obs.metrics import Registry
from fluxdistributed_tpu.train import (
    GuardConfig,
    GuardHalt,
    TrainGuard,
    prepare_training,
    read_resume_manifest,
    replay_item,
    resume_training,
    train,
)
from fluxdistributed_tpu.train.logging import NullLogger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CYCLES = 8


@pytest.fixture(autouse=True)
def _clean_plan():
    yield
    faults.clear_plan()


def make_task(mesh=None, cycles=CYCLES, zero1=False):
    ds = SyntheticDataset(nsamples=64, nclasses=10, shape=(8, 8, 3))
    return prepare_training(
        MLP(features=(10, 10)), ds, optim.adam(1e-3),
        mesh=mesh, batch_size=8, cycles=cycles, topk=(),
        zero1=zero1, guard=True)


def record_losses(task):
    losses = []
    orig = task.step_fn

    def wrapped(state, batch):
        out = orig(state, batch)
        losses.append(float(out[1]["loss"]))
        return out

    task.step_fn = wrapped
    return losses


def assert_params_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def plan(*entries):
    faults.install_plan(faults.FaultPlan.from_spec({"fail": list(entries)}))


# ---------------------------------------------------------------------------
# policy engine units (no jax, no trainer)
# ---------------------------------------------------------------------------


def guard_with(reg=None, **kw):
    return TrainGuard(GuardConfig(**kw), registry=reg or Registry(),
                      logger=NullLogger())


def test_zscore_warmup_and_spike():
    g = guard_with(warmup=4, zmax=6.0)
    # warmup: non-finite always detected, spikes not yet
    assert g.zscore(99.0) is None
    for i in range(6):
        assert g.observe(i, {"loss": 1.0 + 0.01 * (i % 2)}) == "ok"
    z = g.zscore(50.0)
    assert z is not None and z > 6.0
    assert g.observe(6, {"loss": 50.0}) == "skip"
    assert g.is_quarantined(6)
    # the spike was NOT absorbed into the baseline
    assert g.zscore(50.0) > 6.0
    # and normal losses keep flowing
    assert g.observe(7, {"loss": 1.0}) == "ok"


def test_zscore_zero_mad_epsilon_floor():
    g = guard_with(warmup=4)
    for i in range(5):
        g.observe(i, {"loss": 2.0})  # bit-constant window, MAD = 0
    assert math.isfinite(g.zscore(2.0)) and abs(g.zscore(2.0)) < 1e-6
    assert g.zscore(2.1) > 1e3  # any deviation registers


def test_nonfinite_sentinel_detection():
    g = guard_with(rollback_after=10)  # stay on the skip tier here
    assert g.observe(0, {"guard": np.array([1.0, 0.5])}) == "ok"
    assert g.observe(1, {"guard": np.array([np.nan, 0.5])}) == "skip"
    assert g.observe(2, {"guard": np.array([1.0, np.inf])}) == "skip"
    # loss-only fallback (no compiled sentinel)
    assert g.observe(3, {"loss": np.float32("nan")}) == "skip"
    assert sorted(g.quarantined_items()) == [1, 2, 3]


def test_policy_ladder_rollback_then_halt():
    g = guard_with(rollback_after=2, anomaly_window=8, max_rollbacks=1,
                   progress_steps=4)
    bad = {"guard": np.array([np.nan, 1.0])}
    assert g.observe(0, bad) == "skip"
    assert g.observe(1, bad) == "rollback"      # 2 within the window
    assert g.observe(2, bad) == "skip"          # window reset post-rollback
    assert g.observe(3, bad) == "halt"          # debt 1 == max_rollbacks
    err = g.halt("test")
    assert isinstance(err, GuardHalt) and err.retryable is False
    assert err.quarantined == [0, 1, 2, 3]


def test_progress_clears_rollback_debt():
    g = guard_with(rollback_after=2, anomaly_window=4, max_rollbacks=1,
                   progress_steps=3)
    bad = {"guard": np.array([np.nan, 1.0])}
    assert g.observe(0, bad) == "skip"
    assert g.observe(1, bad) == "rollback"
    for i in range(2, 5):
        assert g.observe(i, {"guard": np.array([1.0, 1.0])}) == "ok"
    # debt cleared: the next persistent anomaly may roll back again
    assert g.observe(10, bad) == "skip"
    assert g.observe(11, bad) == "rollback"


def test_guard_metrics_names():
    reg = Registry()
    g = guard_with(reg=reg)
    g.observe(0, {"guard": np.array([np.nan, 1.0])})
    text = reg.prometheus_text()
    for name in ("fdtpu_guard_anomalies_total", "fdtpu_guard_quarantined_total",
                 "fdtpu_guard_quarantine_size", "fdtpu_guard_last_z",
                 "fdtpu_guard_grad_norm", "fdtpu_guard_rollbacks_total",
                 "fdtpu_guard_halts_total"):
        assert name in text, name
    assert reg.value("fdtpu_guard_anomalies_total", "nonfinite") == 1


def test_guard_config_validation():
    with pytest.raises(ValueError, match="window"):
        GuardConfig(window=1)
    with pytest.raises(ValueError, match="zmax"):
        GuardConfig(zmax=0)
    with pytest.raises(ValueError, match="rollback_after"):
        GuardConfig(rollback_after=0)


# ---------------------------------------------------------------------------
# the compiled sentinel
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def sentinel_task():
    return make_task()


def test_sentinel_shape_and_values(sentinel_task):
    task = sentinel_task
    batch = next(iter(task.loader))
    _, m = task.step_fn(task.state, batch)
    g = np.asarray(m["guard"])
    assert g.shape == (2,)
    assert g[0] == np.float32(m["loss"])  # bit-equal when grads finite
    assert g[1] > 0 and np.isfinite(g).all()


def test_sentinel_poisoned_by_nan_input(sentinel_task):
    task = sentinel_task
    batch = next(iter(task.loader))
    bad = dict(batch)
    img = np.asarray(batch["image"]).copy()
    img[0, 0, 0, 0] = np.nan  # one poisoned pixel
    bad["image"] = img
    _, m = task.step_fn(task.state, bad)
    g = np.asarray(m["guard"])
    assert not np.isfinite(g[0])  # the any-reduce caught it


def test_prepare_guard_validation():
    ds = SyntheticDataset(nsamples=16, nclasses=4, shape=(8, 8, 3))
    with pytest.raises(ValueError, match="donate=False"):
        prepare_training(MLP(features=(4,)), ds, optim.adam(1e-3),
                         batch_size=8, cycles=2, topk=(),
                         guard=True, donate=True)
    with pytest.raises(ValueError, match="loss-only"):
        prepare_training(MLP(features=(4,)), ds, optim.adam(1e-3),
                         batch_size=8, cycles=2, topk=(),
                         guard=True, spmd="fsdp")


# ---------------------------------------------------------------------------
# acceptance: quarantine parity (bit-identical to a clean skip run)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def clean_skip2():
    """A clean guarded run that deterministically skips item 2 — the
    parity oracle for every injected-anomaly run below."""
    task = make_task()
    losses = record_losses(task)
    params, _, _ = train(task, print_every=0, eval_every=0,
                         logger=NullLogger(),
                         guard=GuardConfig(quarantine=(2,)))
    return params, losses


@pytest.mark.parametrize("site,action", [("train.loss", "nan"),
                                         ("train.grad", "inf")])
def test_injected_anomaly_matches_clean_skip(clean_skip2, site, action):
    clean_params, clean_losses = clean_skip2
    task = make_task()
    losses = record_losses(task)
    plan({"site": site, "at": 2, "action": action})
    params, _, task = train(task, print_every=0, eval_every=0,
                            logger=NullLogger(), guard=GuardConfig())
    # item 2 was stepped (its loss recorded) then DISCARDED
    assert len(losses) == len(clean_losses) + 1
    del losses[2]
    assert losses == clean_losses
    assert_params_equal(params, clean_params)
    assert task.quarantined_items == [2]


def test_guard_policy_is_transparent_without_anomalies():
    """No anomalies -> the guard policy commits every step: the loss
    stream is bit-identical to the same compiled (guarded) step run
    with no policy engine at all."""
    t1 = make_task()
    l1 = record_losses(t1)
    train(t1, print_every=0, eval_every=0, logger=NullLogger(),
          guard=GuardConfig())
    assert t1.quarantined_items == []
    t2 = make_task()
    l2 = record_losses(t2)
    train(t2, print_every=0, eval_every=0, logger=NullLogger())
    assert l1 == l2


# ---------------------------------------------------------------------------
# rollback tier
# ---------------------------------------------------------------------------


ROLLBACK_CFG = dict(rollback_after=3, anomaly_window=8)


def test_rollback_matches_clean_skip_run(tmp_path):
    clean = make_task(cycles=10)
    clean_params, _, _ = train(
        clean, print_every=0, eval_every=0, logger=NullLogger(),
        guard=GuardConfig(quarantine=(3, 4, 5), **ROLLBACK_CFG))

    task = make_task(cycles=10)
    plan({"site": "train.loss", "at": 3, "action": "nan"},
         {"site": "train.grad", "at": 4, "action": "inf"},
         {"site": "train.loss", "at": 5, "action": "nan"})
    reg_before = _guard_counter("fdtpu_guard_rollbacks_total")
    params, _, task = train(
        task, print_every=0, eval_every=0, logger=NullLogger(),
        checkpoint_dir=str(tmp_path), checkpoint_every=2,
        guard=GuardConfig(**ROLLBACK_CFG))
    assert_params_equal(params, clean_params)
    assert task.quarantined_items == [3, 4, 5]
    assert _guard_counter("fdtpu_guard_rollbacks_total") == reg_before + 1
    # a COMPLETED run clears the guard manifest like any other
    assert read_resume_manifest(tmp_path) is None


def _guard_counter(name):
    from fluxdistributed_tpu.obs import get_registry

    return get_registry().value(name)


def test_rollback_loop_halts_with_manifest(tmp_path):
    task = make_task()
    plan({"site": "train.loss", "times": 99, "action": "nan"})
    with pytest.raises(GuardHalt) as ei:
        train(task, print_every=0, eval_every=0, logger=NullLogger(),
              checkpoint_dir=str(tmp_path), checkpoint_every=2,
              guard=GuardConfig(rollback_after=2, anomaly_window=8,
                                max_rollbacks=1))
    assert ei.value.retryable is False
    # the halt left a consistent (checkpoint, cursor, quarantine) triple
    m = read_resume_manifest(tmp_path)
    assert m is not None and m["reason"] == "guard"
    assert m["quarantined_items"] == ei.value.quarantined
    assert m["checkpoint_step"] == 0 and m["next_item"] == 0


def test_rollback_without_checkpoint_dir_halts():
    task = make_task()
    plan({"site": "train.loss", "times": 99, "action": "nan"})
    with pytest.raises(GuardHalt, match="no checkpoint_dir"):
        train(task, print_every=0, eval_every=0, logger=NullLogger(),
              guard=GuardConfig(rollback_after=2, anomaly_window=8))


# ---------------------------------------------------------------------------
# rollback x ZeRO-1 x resume interplay (the satellite)
# ---------------------------------------------------------------------------


def test_rollback_zero1_sigterm_elastic_resume(tmp_path):
    """Injected-NaN rollback, then SIGTERM, then elastic resume (8->4):
    step-for-step loss-parity with a clean run that skipped the same
    batches.  Every robustness layer stacked: sentinel detection,
    quarantine, rollback replay, checkpoint-on-signal, manifest
    round-trip, ZeRO-1 flat-shard re-split."""
    clean = make_task(cycles=10, zero1=True)
    clean_losses = record_losses(clean)
    train(clean, print_every=0, eval_every=0, logger=NullLogger(),
          guard=GuardConfig(quarantine=(3, 4, 5), **ROLLBACK_CFG))

    task = make_task(cycles=10, zero1=True)
    faults.install_plan(
        faults.FaultPlan.from_spec(
            {"fail": [{"site": "train.loss", "at": 3, "action": "nan"},
                      {"site": "train.loss", "at": 4, "action": "nan"},
                      {"site": "train.grad", "at": 5, "action": "inf"}]}
        ).sigterm_at_step(7))
    head = record_losses(task)
    with pytest.raises(faults.Preempted):
        train(task, print_every=0, eval_every=0, logger=NullLogger(),
              checkpoint_dir=str(tmp_path), checkpoint_every=2,
              handle_signals=True, guard=GuardConfig(**ROLLBACK_CFG))
    faults.clear_plan()
    m = read_resume_manifest(tmp_path)
    assert m is not None and m["next_item"] == 7
    assert m["quarantined_items"] == [3, 4, 5]

    # elastic: the next grant hands back HALF the devices
    resumed = make_task(cycles=10, zero1=True, mesh=data_mesh(4))
    tail = record_losses(resumed)
    manifest = resume_training(resumed, str(tmp_path))
    assert manifest is not None
    assert resumed.quarantined_items == [3, 4, 5]
    train(resumed, print_every=0, eval_every=0, logger=NullLogger(),
          checkpoint_dir=str(tmp_path), checkpoint_every=0,
          guard=GuardConfig(**ROLLBACK_CFG))
    # strip the three discarded anomaly steps (the injected corruption
    # hits the OBSERVED sentinel, so the recorded losses stay finite —
    # only position, not finiteness, identifies them): the ACCEPTED
    # stream must match the oracle
    accepted = _strip_discarded(head, tail)
    np.testing.assert_allclose(
        np.asarray(accepted), np.asarray(clean_losses),
        rtol=1e-4, atol=1e-6)
    assert read_resume_manifest(tmp_path) is None


def _strip_discarded(head, tail):
    """The guarded run's recorded losses minus the three discarded
    anomaly steps (items 3,4,5 stepped once each, then skipped on the
    rollback replay): what remains is the accepted stream."""
    # items run pre-rollback: 0,1,2,3(bad),4(bad),5(bad -> rollback);
    # replay from the step-2 checkpoint skips 3,4,5 -> 6; sigterm at 7.
    return head[:3] + head[6:] + tail


def test_rollback_after_elastic_resume(tmp_path):
    """Anomalies AFTER an 8->4 elastic resume roll back onto a
    checkpoint with the NEW topology's ZeRO-1 flat-pad layout: guarded
    train() re-banks the baseline on start, so the rollback is a plain
    same-topology restore (without the re-bank it would try to restore
    the old device count's pad shapes and fail)."""
    clean = make_task(cycles=10, zero1=True)
    clean_losses = record_losses(clean)
    train(clean, print_every=0, eval_every=0, logger=NullLogger(),
          guard=GuardConfig(quarantine=(6, 7, 8), **ROLLBACK_CFG))

    task = make_task(cycles=10, zero1=True)
    head = record_losses(task)
    faults.install_plan(faults.FaultPlan().sigterm_at_step(6))
    with pytest.raises(faults.Preempted):
        train(task, print_every=0, eval_every=0, logger=NullLogger(),
              checkpoint_dir=str(tmp_path), checkpoint_every=2,
              handle_signals=True, guard=GuardConfig(**ROLLBACK_CFG))
    faults.clear_plan()

    resumed = make_task(cycles=10, zero1=True, mesh=data_mesh(4))
    tail = record_losses(resumed)
    resume_training(resumed, str(tmp_path))
    plan({"site": "train.loss", "at": 6, "action": "nan"},
         {"site": "train.loss", "at": 7, "action": "nan"},
         {"site": "train.grad", "at": 8, "action": "inf"})
    train(resumed, print_every=0, eval_every=0, logger=NullLogger(),
          checkpoint_dir=str(tmp_path), checkpoint_every=2,
          guard=GuardConfig(**ROLLBACK_CFG))
    assert resumed.quarantined_items == [6, 7, 8]
    # tail = items 6,7,8 (stepped then discarded; third triggered the
    # rollback) then the replay skips them and item 9 is accepted
    accepted = head + tail[3:]
    np.testing.assert_allclose(
        np.asarray(accepted), np.asarray(clean_losses),
        rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# replay harness
# ---------------------------------------------------------------------------


def test_replay_item_reproduces_step(sentinel_task):
    task = sentinel_task
    report = replay_item(task, 2)
    assert report["item"] == 2 and report["finite"] is True
    assert report["sentinel"] == "compiled"
    assert len(report["loss"]) == 1 and len(report["grad_norm"]) == 1
    # deterministic: same (seed, process, item) derivation, same state
    again = replay_item(task, 2, debug_nans=False)
    assert again["loss"] == report["loss"]
    with pytest.raises(ValueError, match="outside"):
        replay_item(task, 10**6)


# ---------------------------------------------------------------------------
# driver e2e (subprocess; slow tier)
# ---------------------------------------------------------------------------


def _driver_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _driver(extra, tmp_path, devices=8):
    return subprocess.run(
        [sys.executable, os.path.join("bin", "driver.py"),
         "--model", "SimpleCNN", "--dataset", "synthetic",
         "--num-classes", "4", "--image-size", "8",
         "--batch-size", "8", "--cycles", "6",
         "--print-every", "0", "--eval-every", "0",
         "--checkpoint-dir", str(tmp_path / "ck"),
         "--checkpoint-every", "0", "--guard",
         "--platform", "cpu", "--local-devices", str(devices),
         *extra],
        capture_output=True, text=True, timeout=600, env=_driver_env(),
        cwd=REPO,
    )


@pytest.mark.slow
def test_driver_guard_quarantine_e2e(tmp_path):
    """--guard + an injected NaN completes the run (quarantining the
    batch), and --replay-step re-executes the quarantined item from the
    checkpoint + cursor for diagnosis."""
    p = _driver(["--fault-plan",
                 '{"fail": [{"site": "train.loss", "at": 2, '
                 '"action": "nan"}]}'], tmp_path)
    assert p.returncode == 0, (p.stdout[-1500:], p.stderr[-1500:])
    assert "guard: nonfinite anomaly at item 2" in (p.stdout + p.stderr)
    assert "done: 5 steps" in p.stdout, p.stdout[-1500:]

    r = _driver(["--resume", "--replay-step", "2"], tmp_path)
    assert r.returncode == 0, (r.stdout[-1500:], r.stderr[-1500:])
    report = json.loads(r.stdout.strip().splitlines()[-1])
    assert report["item"] == 2 and report["finite"] is True


@pytest.mark.slow
def test_driver_guard_halt_rc(tmp_path):
    """A rollback loop exits with the DISTINCT rc 65 (EX_DATAERR) and
    says retryable: false — the supervisor's stop signal."""
    p = _driver(["--checkpoint-every", "2", "--guard-rollback-after", "2",
                 "--fault-plan",
                 '{"fail": [{"site": "train.loss", "times": 99, '
                 '"action": "nan"}]}'], tmp_path)
    assert p.returncode == faults.HALTED_RC, (
        p.returncode, p.stdout[-1500:], p.stderr[-1500:])
    assert "retryable: false" in p.stdout
