"""FDT105 positive: axis-name literals not sourced from mesh.py."""
from jax.sharding import PartitionSpec as P


def bogus_spec():
    return P("nonexistent_axis")  # unknown axis: GSPMD compile error


def hardcoded_spec():
    return P("data", None)  # declared axis, but a drifting copy


def shard_over(mesh, batch_axis="data"):  # hardcoded default
    return mesh.shape[batch_axis]


PIPE_AXIS = "pipe"  # re-declares mesh.py's literal


def stage_count(mesh):
    return mesh.shape["pipe"]  # literal mesh-shape lookup


def bogus_rule_table(ShardLargest):
    # rule-table value naming an undeclared axis: resolution rejects it
    return [(r".*", ShardLargest("nonexistent_axis"))]


def hardcoded_rule_table(ShardLargest):
    # declared axis, but a drifting string copy
    return [(r".*", ShardLargest(axis="fsdp"))]
