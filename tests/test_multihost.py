"""Multi-process (fake multi-host) integration tests.

Spawns real OS processes that form a JAX distributed runtime over
localhost gloo — the CPU stand-in for a TPU pod slice's ICI/DCN.  This
covers the territory the reference's process-DDP mode (src/sync.jl +
bin/driver.jl) occupies but never tests (SURVEY §4: "Multi-process mode
has no tests at all").
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

# Every test here spawns real OS processes (multi-minute wall-clock);
# module-level mark so additions inherit it and the tier-1
# ``-m 'not slow'`` lane stays fast — full CI still runs them.
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _scrubbed_env() -> dict:
    """Child env without the parent's fake-device/platform pins: the
    worker configures its own platform via jax.config."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _run_procs(cmds, timeout=600):
    env = _scrubbed_env()
    procs = [
        subprocess.Popen(
            c, cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for c in cmds
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed (rc={p.returncode}):\n{out[-4000:]}"
    return outs


@pytest.mark.slow
def test_two_process_training_and_collectives():
    """2 processes x 2 CPU devices: global batch assembly, a 3-step DP
    train run with cross-process grad all-reduce, replica identity,
    cooperative abort."""
    port = _free_port()
    outs = _run_procs(
        [
            [sys.executable, os.path.join("tests", "_mh_worker.py"), str(i), "2", str(port)]
            for i in range(2)
        ]
    )
    for i, out in enumerate(outs):
        assert f"worker {i}: OK" in out, out[-2000:]
        assert f"worker {i}: device-loop OK" in out, out[-2000:]


@pytest.mark.slow
def test_two_process_pipeline_and_moe():
    """2 processes x 4 CPU devices: the GPipe ppermute ring and the MoE
    dispatch/return all_to_alls cross a real process boundary (the DCN
    stand-in), forward AND backward, with shard-level parity against
    dense references computed locally in each worker."""
    port = _free_port()
    outs = _run_procs(
        [
            [sys.executable, os.path.join("tests", "_mh_ppep_worker.py"),
             str(i), "2", str(port)]
            for i in range(2)
        ]
    )
    for i, out in enumerate(outs):
        assert f"worker {i}: OK" in out, out[-3000:]
        for part in ("PP forward", "PP backward", "1F1B cross-process",
                     "EP forward", "EP backward"):
            assert f"{part} parity OK" in out, (part, out[-3000:])


@pytest.mark.slow
def test_driver_cli_fake_cluster():
    """bin/driver.py end-to-end in manual bring-up mode — the analog of
    the reference's bin/driver.jl session, minus the channel plumbing."""
    port = _free_port()
    common = [
        sys.executable,
        os.path.join("bin", "driver.py"),
        "--model", "SimpleCNN", "--dataset", "synthetic",
        "--num-classes", "10", "--image-size", "24",
        "--batch-size", "8", "--cycles", "3",
        "--opt", "momentum", "--lr", "0.05",
        "--print-every", "1", "--eval-every", "0",
        "--coordinator", f"localhost:{port}",
        "--num-processes", "2", "--platform", "cpu", "--local-devices", "2",
    ]
    outs = _run_procs([common + ["--process-id", str(i)] for i in range(2)])
    assert "done: 3 steps" in outs[0], outs[0][-2000:]
    assert "4 (2/host x 2 hosts)" in outs[0], outs[0][-2000:]


@pytest.mark.slow
def test_driver_cli_fake_cluster_fsdp(tmp_path):
    """Multi-host FSDP end-to-end: params/opt state sharded ACROSS
    processes, training steps, checkpoint saved sharded, resume works —
    covering the cross-process gather (tree.to_host process_allgather)
    and the abstract sharded restore path."""
    port = _free_port()
    ck = str(tmp_path / "ck")
    common = [
        sys.executable,
        os.path.join("bin", "driver.py"),
        "--model", "SimpleCNN", "--dataset", "synthetic",
        "--num-classes", "10", "--image-size", "24",
        "--batch-size", "8", "--cycles", "3",
        "--opt", "momentum", "--lr", "0.05",
        "--print-every", "1", "--eval-every", "0",
        "--spmd", "fsdp",
        "--checkpoint-dir", ck, "--checkpoint-every", "2",
        "--coordinator", f"localhost:{port}",
        "--num-processes", "2", "--platform", "cpu", "--local-devices", "2",
    ]
    outs = _run_procs([common + ["--process-id", str(i)] for i in range(2)])
    assert "done: 3 steps" in outs[0], outs[0][-2000:]

    # resume from the sharded checkpoint on a fresh 2-process cluster
    port2 = _free_port()
    common[common.index(f"localhost:{port}")] = f"localhost:{port2}"
    outs = _run_procs(
        [common + ["--process-id", str(i), "--resume"] for i in range(2)]
    )
    assert "resumed from step 3" in outs[0], outs[0][-2000:]
    assert "done: 6 steps" in outs[0], outs[0][-2000:]
