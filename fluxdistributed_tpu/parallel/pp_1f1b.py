"""Hand-scheduled 1F1B pipeline parallelism.

The GPipe schedule in ``parallel.pp`` derives its backward pass from AD:
differentiate through the forward ``lax.scan`` and the reverse pipeline
falls out.  Elegant — but the scan transpose stores residuals for every
tick, so activation memory grows with the microbatch count M.  That is
GPipe's textbook pathology, and it is measurable: on the benchmark mesh,
per-tick cost inflates >2x from M=S to M=8S as the stashed residuals
grow (benchmarks/pp_bubble.py, docs/parallelism.md).

This module hand-writes the 1F1B (one-forward-one-backward) schedule
instead, the way Megatron-LM runs its pipelines — but TPU-idiomatic:
the whole schedule (all forwards, all backwards, gradient accumulation)
is ONE ``lax.scan`` over lockstep ticks inside ONE ``shard_map``, with
neighbor transfers as ``ppermute`` collectives.  Per tick each pipe
device performs one stage-forward, one stage-backward, or idles,
according to a STATIC schedule table computed in Python at trace time
(S and M are static, so the whole timetable is).  Nothing here is
data-dependent control flow: per-device divergence is a ``lax.cond`` on
a device-varying flag read from the table.

Memory property (the point of 1F1B): a device stashes at most
``min(S, M)`` in-flight microbatch INPUTS — a fixed-size ring buffer —
instead of the O(M·ticks) residuals of AD-through-scan.  Backward ticks
recompute the stage forward under ``jax.vjp`` from the stored input
(same recompute trade as ``pipeline_apply(remat=True)``, which is how
Megatron runs production pipelines too: activation recompute +
schedule).  Net: activation memory O(S), not O(M), so M — and with it
the (S-1)/(M+S-1) bubble — can grow freely.

Because forward and backward interleave *within* the schedule, the loss
must be computable per-microbatch inside the pipeline: the caller
provides ``embed_fn`` (applied at stage 0, e.g. token embedding) and
``head_fn`` (applied at stage S-1: final norm + logits + scalar loss).
Stage-parameter gradients stay local to their pipe device (no gradient
collective at all); ``embed_fn``/``head_fn`` ("outer") parameter
gradients accumulate on devices 0 and S-1 and are summed across the
pipe axis once at the end — which also makes weight tying (embedding
matrix used by both ends) come out right for free.

Reference anchor: net-new scope beyond FluxDistributed.jl (SURVEY §2
"PP: NO"); the reference never pipelines.  Schedule follows the
published 1F1B form (PipeDream-flush / Megatron-LM); implementation is
original and TPU-first.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..optim import Optimizer
from .dp import TrainState
from .pp import PIPE_AXIS, _accepts_stage

Pytree = Any

__all__ = ["Schedule1F1B", "build_schedule", "pipeline_grads_1f1b",
           "make_train_step_1f1b", "split_state_shardings", "SCHEDULES"]

#: the hand-written schedules this module compiles: classic 1F1B, and
#: the ZB-H1-style zero-bubble variant that splits each microbatch
#: backward into an input-grad (B) tick and a deferred weight-grad (W)
#: tick so W work fills the drain bubble (arXiv:2401.10241's
#: handcrafted form, adapted to the recompute-from-ring regime)
SCHEDULES = ("1f1b", "zb")


def split_state_shardings(mesh: Mesh, axis: str = PIPE_AXIS) -> Callable:
    """``state_shardings(state)`` builder for the split param tree
    ``{"outer": ..., "stages": ...}``: outer replicated, stages sharded
    on ``axis``, optimizer state following its param.  The single source
    of truth for both pipeline schedules (``lm_pp``/``lm_pp_1f1b`` reuse
    it, and ``make_train_step_1f1b`` compiles with it)."""
    from ..sharding import make_shardings
    from .tp import state_specs

    def state_shardings(state: TrainState) -> TrainState:
        p_specs = {
            "outer": jax.tree.map(lambda _: P(), state.params["outer"]),
            "stages": jax.tree.map(lambda _: P(axis), state.params["stages"]),
        }
        return make_shardings(state_specs(state, p_specs), mesh)

    return state_shardings


class Schedule1F1B(NamedTuple):
    """Static lockstep timetable: ``[T, S]`` arrays, one row per tick.

    ``is_fwd[t, i]``/``is_bwd[t, i]`` — does device i run a stage
    forward / backward at tick t (at most one of the two is set);
    ``fwd_mb``/``bwd_mb`` — which microbatch (0 when inactive);
    ``fwd_chunk``/``bwd_chunk`` — which of the device's V interleaved
    chunks (always 0 when V = 1): selects the chunk's params and its
    input-ring slab;
    ``fwd_slot``/``bwd_slot`` — the ring-buffer slot inside that chunk;
    ``fwd_latch``/``bwd_latch`` — FLAT index (``chunk·D + mb mod D``)
    into the depth-D latch buffers a consuming tick reads from;
    ``recv_act[t, i]`` — the neighbor to the left (ring order) produced
    an activation this tick, so latch the incoming ppermute value at
    flat index ``recv_act_ix[t, i]``; ``recv_cot``/``recv_cot_ix`` —
    same for cotangents from the right.

    ``ring`` — input-ring slots per chunk; ``n_chunks`` — V;
    ``latch_depth`` — D latch slots per chunk per direction.

    Zero-bubble timetables (``schedule="zb"``) additionally carry
    ``is_w``/``w_mb``/``w_chunk``/``w_slot`` — the deferred weight-grad
    (W) pass of each microbatch, reading the stashed input AND the
    cotangent the B tick banked at the same ``m % ring`` slot; the
    input-ring slot retires at W, not B.  For ``schedule="1f1b"`` the W
    columns are all-zero and the runtime never reads them.
    """

    is_fwd: np.ndarray
    is_bwd: np.ndarray
    fwd_mb: np.ndarray
    bwd_mb: np.ndarray
    fwd_chunk: np.ndarray
    bwd_chunk: np.ndarray
    fwd_slot: np.ndarray
    bwd_slot: np.ndarray
    fwd_latch: np.ndarray
    bwd_latch: np.ndarray
    recv_act: np.ndarray
    recv_act_ix: np.ndarray
    recv_cot: np.ndarray
    recv_cot_ix: np.ndarray
    ring: int
    n_chunks: int
    latch_depth: int
    max_in_flight: int
    is_w: np.ndarray = None
    w_mb: np.ndarray = None
    w_chunk: np.ndarray = None
    w_slot: np.ndarray = None
    schedule: str = "1f1b"

    @property
    def ticks(self) -> int:
        return self.is_fwd.shape[0]

    def busy_per_device(self) -> np.ndarray:
        """Scheduled actions per device over the T ticks (F + B, plus W
        for zero-bubble timetables) — ``[S]`` ints."""
        busy = self.is_fwd.sum(axis=0) + self.is_bwd.sum(axis=0)
        if self.is_w is not None:
            busy = busy + self.is_w.sum(axis=0)
        return busy.astype(np.int64)

    @property
    def idle_ticks(self) -> np.ndarray:
        """Idle ticks per device — the bubble, counted where it sits."""
        return self.ticks - self.busy_per_device()

    @property
    def utilization(self) -> float:
        """Busy fraction over all devices and ticks (every device
        performs the same action count, so this equals any single
        device's busy share)."""
        S = self.is_fwd.shape[1]
        return float(self.busy_per_device().sum()) / (self.ticks * S)

    def render(self, max_ticks: Optional[int] = None) -> str:
        """ASCII timetable, one row per device, one column per tick:
        ``F3``/``B3``/``W3`` = forward / input-grad backward /
        weight-grad of microbatch 3 (lowercase letter + chunk digit
        when V > 1, e.g. ``f1:3`` → chunk 1, microbatch 3), ``.`` =
        idle.  Each device row ends with its idle-tick count — the
        per-device bubble at a glance.  Interleaved (V > 1) layouts
        render in full by default; pass ``max_ticks`` to truncate wide
        timetables instead.  Eyeball the warmup ramp, the steady state,
        and the (W-filled, for zb) drain directly:

        >>> print(build_schedule(4, 8).render())
        """
        T, S = self.is_fwd.shape
        V = self.n_chunks
        shown = T if max_ticks is None else min(T, max_ticks)
        cells = []
        width = 0
        for i in range(S):
            row = []
            for t in range(shown):
                if self.is_fwd[t, i]:
                    c = (f"F{self.fwd_mb[t, i]}" if V == 1 else
                         f"f{self.fwd_chunk[t, i]}:{self.fwd_mb[t, i]}")
                elif self.is_bwd[t, i]:
                    c = (f"B{self.bwd_mb[t, i]}" if V == 1 else
                         f"b{self.bwd_chunk[t, i]}:{self.bwd_mb[t, i]}")
                elif self.is_w is not None and self.is_w[t, i]:
                    c = (f"W{self.w_mb[t, i]}" if V == 1 else
                         f"w{self.w_chunk[t, i]}:{self.w_mb[t, i]}")
                else:
                    c = "."
                width = max(width, len(c))
                row.append(c)
            cells.append(row)
        idle = self.idle_ticks
        lines = [
            f"dev{i} " + " ".join(c.rjust(width) for c in row)
            + f"  idle={int(idle[i])}"
            for i, row in enumerate(cells)
        ]
        tail = "" if T <= shown else f"\n... ({T - shown} more ticks)"
        name = "ZB" if self.schedule == "zb" else "1F1B"
        head = (f"{name} schedule: S={S} M={int(self.is_fwd[:, 0].sum()) // V} "
                f"V={V} T={T} util={self.utilization:.3f} "
                f"in-flight<={self.max_in_flight}")
        return head + "\n" + "\n".join(lines) + tail


def _verify_placement(S: int, M: int, V: int, ring: int, D: int,
                      fdone, bdone, wdone=None) -> None:
    """The dependency oracle: PROVE a placement safe for the runtime's
    fixed-size buffers, raising ``RuntimeError`` on the first violated
    invariant.  ``fdone``/``bdone``/``wdone`` are tick-of-action arrays
    ``[device][chunk][mb]`` (``wdone=None`` = classic 1F1B, where the
    backward is one joint tick).

    Checked, for every edge/chunk/slot:

    * **act/cot order + latch safety** — a produced activation (left
      neighbor's F, or the S-1 → 0 chunk wrap) / cotangent (right
      neighbor's B, or the 0 → S-1 wrap) lands strictly before its
      consumer fires, and is consumed before the producer's D-th next
      value for that chunk overwrites the latch;
    * **action order** (zb) — F(m) < B(m) < W(m) on each (device,
      chunk);
    * **ring safety** — an input's ``m % ring`` slot is not reused by
      F(m+ring) until its occupant retires: at B for 1F1B, at W for zb
      (W re-reads the stashed input for the weight-grad recompute);
    * **cot-stash safety** (zb) — the cotangent B(m) banks at
      ``m % ring`` survives until W(m) consumes it, i.e. B(m+ring)
      lands after W(m).

    Exposed at module level so tests can feed deliberately corrupted
    placements and property-test the oracle itself — a proof that never
    fires proves nothing.  Real exceptions, not asserts: a placement
    bug here means silently corrupted gradients at runtime, and asserts
    vanish under ``-O``.
    """
    def _prove(ok: bool, i: int, c: int, m: int, what: str):
        if not ok:
            raise RuntimeError(
                f"pipeline schedule unsafe for S={S}, M={M}, V={V}: "
                f"{what} (device {i}, chunk {c}, microbatch {m})"
            )

    retire = wdone if wdone is not None else bdone
    for c in range(V):
        for i in range(S):
            # activation latch into device i's chunk c: produced by the
            # left neighbor (or the S-1 -> 0 wrap from chunk c-1)
            if i > 0:
                prod = [fdone[i - 1][c][m] for m in range(M)]
            elif c > 0:
                prod = [fdone[S - 1][c - 1][m] for m in range(M)]
            else:
                prod = None  # embeds, no latch
            if prod is not None:
                cons = [fdone[i][c][m] for m in range(M)]
                for m in range(M):
                    _prove(prod[m] < cons[m], i, c, m, "act order")
                    if m + D < M:
                        _prove(prod[m + D] >= cons[m], i, c, m,
                               "act latch overwritten before consumption")
            # cotangent latch into device i's chunk c: produced by the
            # right neighbor (or the 0 -> S-1 wrap from chunk c+1)
            if i < S - 1:
                prod = [bdone[i + 1][c][m] for m in range(M)]
            elif c < V - 1:
                prod = [bdone[0][c + 1][m] for m in range(M)]
            else:
                prod = None  # local loss, no latch
            if prod is not None:
                cons = [bdone[i][c][m] for m in range(M)]
                for m in range(M):
                    _prove(prod[m] < cons[m], i, c, m, "cot order")
                    if m + D < M:
                        _prove(prod[m + D] >= cons[m], i, c, m,
                               "cot latch overwritten before consumption")
            for m in range(M):
                _prove(fdone[i][c][m] < bdone[i][c][m], i, c, m,
                       "backward before its own forward")
                if wdone is not None:
                    _prove(bdone[i][c][m] < wdone[i][c][m], i, c, m,
                           "weight-grad before its input-grad")
    for i in range(S):  # ring-slot + cot-stash reuse, per chunk
        for c in range(V):
            for m in range(M - ring):
                _prove(fdone[i][c][m + ring] > retire[i][c][m], i, c, m,
                       "ring slot reused while occupant still in flight")
                if wdone is not None:
                    _prove(bdone[i][c][m + ring] > wdone[i][c][m], i, c, m,
                           "cot stash overwritten before its W consumed it")


def build_schedule(S: int, M: int, V: int = 1,
                   schedule: str = "1f1b") -> Schedule1F1B:
    """Build and VERIFY the lockstep timetable for S pipe devices, M
    microbatches, and V interleaved chunks per device (virtual stages;
    logical stage ``c·S + i`` lives on device i as chunk c).
    ``schedule`` picks the discipline: ``"1f1b"`` (one joint backward
    tick per microbatch) or ``"zb"`` (zero-bubble: the backward splits
    into an input-grad B tick and a deferred weight-grad W tick, and
    the dependency-free W work fills idle ticks — above all the drain,
    ZB-H1-style).

    Placement is dependency-driven lockstep greedy list-scheduling.
    Because no single greedy discipline wins across (S, M, V) — the
    1F1B backward-first rule is best for V ≤ 2, forward-first (memory
    gates throttling) often wins at deeper interleave — the builder
    tries a small PORTFOLIO (backward-first / forward-first × latch
    depth D ∈ {1, 2}; for zb, B>F>W vs B>W>F) and keeps the timetable
    with the fewest ticks.  Readiness = upstream forward / downstream
    cotangent placed at a strictly earlier tick, plus the resource
    gates that bound the runtime's buffers: the per-chunk input-ring
    slot gate (in-flight ≤ min(S, M) per chunk; for zb a slot retires
    at W, not B), the depth-D latch gate (a producer may not send value
    m until its consumer consumed m−D), and for zb the cot-stash gate
    (B(m) may not overwrite the stash slot of m−ring before W(m−ring)
    read it).

    For V = 1 the 1F1B backward-first/D=1 member reproduces the classic
    warmup/steady/cooldown sequence and the canonical 2(M+S-1) ticks;
    for V > 1 the fill/drain bubble shrinks toward (S-1)/V chunk-ticks
    per side — the Megatron interleaving effect.  The zb timetable runs
    3·V·M cheaper actions instead of 2·V·M, trading tick count for
    near-zero idle: its drain is W work, not waiting (the returned
    ``utilization``/``idle_ticks`` report the achieved occupancy).

    The builder then PROVES the chosen placement safe for the runtime's
    fixed-size buffers via :func:`_verify_placement` — the dependency
    oracle tests can (and do) feed corrupted placements.
    """
    if S < 2:
        raise ValueError(f"1F1B needs >= 2 pipeline stages, got {S}")
    if M < 1:
        raise ValueError(f"need >= 1 microbatch, got {M}")
    if V < 1:
        raise ValueError(f"need >= 1 chunk per device, got {V}")
    if schedule not in SCHEDULES:
        raise ValueError(
            f"unknown pipeline schedule {schedule!r}; pick one of "
            f"{SCHEDULES}")
    zb = schedule == "zb"

    ring = min(S, M)
    # portfolio: D > 1 only helps interleaved placements; keep V = 1 on
    # the canonical single-latch schedule.  Ties on tick count break
    # toward the placement with fewer in-flight microbatches (less
    # stash memory) — e.g. a forward-greedy member that merely matches
    # backward-first on ticks must not win on memory-hungrier shape.
    if zb:
        prios = ["bfw", "bwf"]
    else:
        prios = ["bfirst", "ffirst"]
    variants = [(p, 1) for p in prios] if V == 1 else \
        [(p, d) for d in (1, 2) for p in prios]
    best = best_key = None
    for prio, depth in variants:
        placed = _place(S, M, V, ring, depth, prio, zb=zb)
        if placed is None:
            continue
        fdone_v, bdone_v, wdone_v, ticks_v, max_if_v = placed
        key = (ticks_v, max_if_v)
        if best_key is None or key < best_key:
            best_key = key
            best = (fdone_v, bdone_v, wdone_v, ticks_v, max_if_v, depth)
    if best is None:
        raise RuntimeError(
            f"{schedule} schedule failed to converge (S={S}, M={M}, V={V})")
    fdone, bdone, wdone, T, max_in_flight, D = best

    _verify_placement(S, M, V, ring, D, fdone, bdone, wdone)

    # ---- timetable arrays from the placement
    shape = (T, S)
    is_fwd = np.zeros(shape, bool)
    is_bwd = np.zeros(shape, bool)
    is_w = np.zeros(shape, bool)
    fwd_mb = np.zeros(shape, np.int32)
    bwd_mb = np.zeros(shape, np.int32)
    w_mb = np.zeros(shape, np.int32)
    fwd_chunk = np.zeros(shape, np.int32)
    bwd_chunk = np.zeros(shape, np.int32)
    w_chunk = np.zeros(shape, np.int32)
    for i in range(S):
        for c in range(V):
            for m in range(M):
                tf, tb = fdone[i][c][m], bdone[i][c][m]
                is_fwd[tf, i], fwd_mb[tf, i], fwd_chunk[tf, i] = True, m, c
                is_bwd[tb, i], bwd_mb[tb, i], bwd_chunk[tb, i] = True, m, c
                if wdone is not None:
                    tw = wdone[i][c][m]
                    is_w[tw, i], w_mb[tw, i], w_chunk[tw, i] = True, m, c

    # receiver-side latch tables: device i latches the incoming
    # activation when its ring-left neighbor fired a forward — into the
    # same chunk, or chunk c+1 across the S-1 -> 0 wrap (the final
    # logical stage's output latches nowhere: it is consumed by the
    # head on device S-1 itself).  Cotangents mirror this to the left.
    # Latch indices are FLAT: chunk·D + (mb mod D).
    recv_act = np.zeros(shape, bool)
    recv_act_ix = np.zeros(shape, np.int32)
    recv_cot = np.zeros(shape, bool)
    recv_cot_ix = np.zeros(shape, np.int32)
    recv_act[:, 1:] = is_fwd[:, :-1]
    recv_act_ix[:, 1:] = fwd_chunk[:, :-1] * D + fwd_mb[:, :-1] % D
    wrap = is_fwd[:, S - 1] & (fwd_chunk[:, S - 1] < V - 1)
    recv_act[:, 0] = wrap
    recv_act_ix[:, 0] = np.where(
        wrap, (fwd_chunk[:, S - 1] + 1) * D + fwd_mb[:, S - 1] % D, 0)
    recv_cot[:, :-1] = is_bwd[:, 1:]
    recv_cot_ix[:, :-1] = bwd_chunk[:, 1:] * D + bwd_mb[:, 1:] % D
    wrap_b = is_bwd[:, 0] & (bwd_chunk[:, 0] > 0)
    recv_cot[:, S - 1] = wrap_b
    recv_cot_ix[:, S - 1] = np.where(
        wrap_b, (bwd_chunk[:, 0] - 1) * D + bwd_mb[:, 0] % D, 0)

    return Schedule1F1B(
        is_fwd, is_bwd, fwd_mb, bwd_mb, fwd_chunk, bwd_chunk,
        (fwd_mb % ring).astype(np.int32), (bwd_mb % ring).astype(np.int32),
        (fwd_chunk * D + fwd_mb % D).astype(np.int32),
        (bwd_chunk * D + bwd_mb % D).astype(np.int32),
        recv_act, recv_act_ix, recv_cot, recv_cot_ix,
        ring, V, D, max_in_flight,
        is_w, w_mb, w_chunk, (w_mb % ring).astype(np.int32),
        schedule,
    )


def _place(S, M, V, ring, D, prio, zb: bool = False):
    """One greedy lockstep placement: returns ``(fdone, bdone, wdone,
    ticks, max_in_flight)`` (tick of each action, [device][chunk][mb];
    peak stashed microbatches on any device; ``wdone`` is None unless
    ``zb``) or None on non-convergence.  ``prio`` picks which ready
    action a device fires: ``bfirst`` retires the oldest ready backward
    (1F1B discipline), ``ffirst`` advances the oldest ready forward and
    lets the memory gates force backwards (depth-first, better at deep
    interleave); the zb disciplines are ``bfw`` (B > F > W: keep the
    pipe fed, W genuinely fills idle ticks) and ``bwf`` (B > W > F:
    retire stash slots eagerly)."""
    fdone = [[[-1] * M for _ in range(V)] for _ in range(S)]
    bdone = [[[-1] * M for _ in range(V)] for _ in range(S)]
    wdone = [[[-1] * M for _ in range(V)] for _ in range(S)] if zb else None
    retire = wdone if zb else bdone  # what frees an input-ring slot

    def f_ready(i, c, m, t):
        if fdone[i][c][m] >= 0:
            return False
        # upstream activation: left neighbor same chunk, or the S-1 -> 0
        # chunk wrap; chunk 0 on device 0 embeds (always ready)
        if i > 0:
            if not 0 <= fdone[i - 1][c][m] < t:
                return False
        elif c > 0:
            if not 0 <= fdone[S - 1][c - 1][m] < t:
                return False
        # ring-slot gate: the slot's previous occupant must be retired
        # (backward for 1F1B; the deferred weight-grad for zb, which
        # re-reads the stashed input)
        prev = m - ring
        if prev >= 0 and retire[i][c][prev] < 0:
            return False
        # forwards of a chunk run in m order (keeps the in-flight window
        # contiguous, which is what makes m % ring collision-free)
        if m > 0 and fdone[i][c][m - 1] < 0:
            return False
        # depth-D latch gate: my activation m-D for this chunk must be
        # consumed before value m lands — the dynamic counterpart of
        # the classic warmup cap S-1-i
        if m >= D:
            if i < S - 1:
                if not 0 <= fdone[i + 1][c][m - D] < t:
                    return False
            elif c < V - 1:
                if not 0 <= fdone[0][c + 1][m - D] < t:
                    return False
        return True

    def b_ready(i, c, m, t):
        if bdone[i][c][m] >= 0 or fdone[i][c][m] < 0:
            return False
        if not fdone[i][c][m] < t:
            return False
        # zb cot-stash gate: B(m) banks its cotangent at slot m % ring,
        # whose previous occupant must have been consumed by its W
        if zb:
            prev = m - ring
            if prev >= 0 and wdone[i][c][prev] < 0:
                return False
        # depth-D latch gate for the cotangent channel (mirror of f_ready)
        if m >= D:
            if i > 0:
                if not 0 <= bdone[i - 1][c][m - D] < t:
                    return False
            elif c > 0:
                if not 0 <= bdone[S - 1][c - 1][m - D] < t:
                    return False
        if i == S - 1 and c == V - 1:
            return True  # loss cotangent is local (own fwd checked above)
        if i < S - 1:
            return 0 <= bdone[i + 1][c][m] < t
        return 0 <= bdone[0][c + 1][m] < t  # 0 -> S-1 chunk wrap

    def w_ready(i, c, m, t):
        # weight-grad: needs only its own B (stashed input + cotangent
        # both local), run in m order per chunk so the stash ring stays
        # a contiguous window
        if wdone[i][c][m] >= 0:
            return False
        if not 0 <= bdone[i][c][m] < t:
            return False
        return m == 0 or wdone[i][c][m - 1] >= 0

    total = S * V * M
    placed_f = placed_b = placed_w = 0
    w_target = total if zb else 0
    t = 0
    # the interleaved critical path alone is 2·S·V ticks (one full
    # logical-pipeline traversal each way), so the non-convergence cap
    # must scale with V·(M+S), not M+S — at S=8, M=1, V=4 the feasible
    # schedule needs exactly 64 ticks.  zb places 3·V·M actions, so its
    # cap scales with the larger action count too.
    cap = (6 if zb else 4) * V * (M + S) + 8
    while placed_f < total or placed_b < total or placed_w < w_target:
        if t > cap:
            return None
        # decide every device against PRE-tick state, commit after
        chosen = []
        for i in range(S):
            pick_b = pick_f = pick_w = None
            for m in range(M):
                for c in reversed(range(V)):
                    if b_ready(i, c, m, t):
                        pick_b = ("B", c, m)
                        break
                if pick_b:
                    break
            for m in range(M):
                for c in range(V):
                    if f_ready(i, c, m, t):
                        pick_f = ("F", c, m)
                        break
                if pick_f:
                    break
            if zb:
                for m in range(M):
                    for c in range(V):
                        if w_ready(i, c, m, t):
                            pick_w = ("W", c, m)
                            break
                    if pick_w:
                        break
            if prio == "bfirst":
                pick = pick_b or pick_f
            elif prio == "ffirst":
                pick = pick_f or pick_b
            elif prio == "bfw":
                pick = pick_b or pick_f or pick_w
            else:  # bwf
                pick = pick_b or pick_w or pick_f
            chosen.append(pick)
        for i, pick in enumerate(chosen):
            if pick is None:
                continue
            act, c, m = pick
            if act == "F":
                fdone[i][c][m] = t
                placed_f += 1
            elif act == "B":
                bdone[i][c][m] = t
                placed_b += 1
            else:
                wdone[i][c][m] = t
                placed_w += 1
        t += 1

    # peak stashed microbatches on any device (fwd done, not yet
    # retired — at B for 1F1B, at W for zb)
    max_if = 0
    for i in range(S):
        events = []
        for c in range(V):
            for m in range(M):
                events.append((fdone[i][c][m], 1))
                events.append((retire[i][c][m], -1))
        events.sort()
        cur = 0
        for _, d in events:
            cur += d
            max_if = max(max_if, cur)
    return fdone, bdone, wdone, t, max_if


def pipeline_grads_1f1b(
    stage_fn: Callable,
    embed_fn: Callable,
    head_fn: Callable,
    mesh: Mesh,
    axis: str = PIPE_AXIS,
    num_microbatches: Optional[int] = None,
    batch_axis: Optional[str] = None,
    interleave: int = 1,
    schedule: str = "1f1b",
):
    """Build ``run(stacked_params, outer, inputs, labels) -> (loss,
    stage_grads, outer_grads)`` executing the full fwd+bwd 1F1B schedule.

    * ``stage_fn(stage_params, x) -> y`` — shape-preserving pipe stage
      (``switch_stage``'s three-argument heterogeneous form — which
      receives the LOGICAL stage index ``chunk·S + device`` — and
      ``chunk_stages``-blocked virtual stages both compose);
    * ``embed_fn(outer, inputs_mb) -> x0`` — entry at logical stage 0,
      re-run under ``vjp`` at backward ticks;
    * ``head_fn(outer, y, labels_mb) -> scalar`` — exit at the final
      logical stage: per-microbatch mean loss.  The pipeline's loss is
      the mean over microbatches; gradients match ``jax.grad`` of that
      composition (tests/test_pp_1f1b.py proves it against the
      unpipelined model).

    ``interleave=V`` runs the Megatron interleaved-virtual-stage
    placement: ``stacked_params`` leaves carry a ``(S, V, ...)`` leading
    layout where ``[i, c]`` is LOGICAL stage ``c·S + i`` (round-robin,
    NOT the blocked ``chunk_stages`` layout), activations wrap
    S-1 → 0 between chunks, and the fill/drain bubble shrinks ~V-fold
    at the cost of V× the per-device latch/ring buffers.

    ``stage_grads`` come back stage-stacked (leading dim sharded on
    ``axis``) exactly like the input params — the optimizer update stays
    local to each pipe device.  ``outer_grads`` are psum'd across the
    pipe axis (embedding contributions from device 0, head contributions
    from device S-1; tied weights sum correctly).  ``batch_axis``
    composes data parallelism on a ``(data, pipe)`` mesh: grads are
    additionally averaged over ``batch_axis`` so each data row sees the
    global mean, matching the framework's DP semantics.

    ``schedule="zb"`` compiles the zero-bubble timetable instead: each
    microbatch's backward splits into an input-grad tick B (recompute
    the stage forward under ``vjp``, pull ONLY the activation cotangent
    through, bank the incoming cotangent in a per-chunk stash ring) and
    a weight-grad tick W (re-run the same ``vjp`` from the stashed
    input + banked cotangent, pull ONLY the parameter grads — plus the
    embed/head outer grads at the end stages).  W depends on nothing
    downstream, so the builder parks W ticks in the bubbles — above all
    the drain (ZB-H1).  Every pulled quantity is the SAME vjp applied
    to the SAME operands as the joint 1F1B backward, so loss and all
    gradients are bit-for-bit identical between the two schedules
    (tests/test_pp_zb.py pins this), and either schedule compiles
    exactly ONCE — the timetable is trace-time static either way.
    """
    S = mesh.shape[axis]
    M = num_microbatches or S
    V = interleave
    zb = schedule == "zb"
    sched = build_schedule(S, M, V, schedule=schedule)
    ring = sched.ring
    with_stage = _accepts_stage(stage_fn)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]
    cols = (
        sched.is_fwd, sched.is_bwd, sched.fwd_mb, sched.bwd_mb,
        sched.fwd_chunk, sched.bwd_chunk,
        sched.fwd_slot, sched.bwd_slot,
        sched.fwd_latch, sched.bwd_latch,
        sched.recv_act, sched.recv_act_ix,
        sched.recv_cot, sched.recv_cot_ix,
    )
    if zb:
        cols = cols + (sched.is_w, sched.w_mb, sched.w_chunk, sched.w_slot)
    rows = tuple(jnp.asarray(a) for a in cols)

    def apply_stage(sp, x, logical_stage):
        return stage_fn(sp, x, logical_stage) if with_stage else stage_fn(sp, x)

    def chunk_tree(sp, c):
        """Device-local params of chunk ``c``; identity when V = 1 (the
        stacked layout then has no chunk dim, preserving the original
        contract)."""
        if V == 1:
            return sp
        return jax.tree.map(
            lambda l: jax.lax.dynamic_index_in_dim(l, c, 0, keepdims=False), sp)

    def chunk_scatter_add(g_sp, gs_c, c):
        """Accumulate a chunk-c gradient into the (V, ...) tree."""
        if V == 1:
            return jax.tree.map(jnp.add, g_sp, gs_c)
        return jax.tree.map(
            lambda gl, gc: jax.lax.dynamic_update_index_in_dim(
                gl, jax.lax.dynamic_index_in_dim(gl, c, 0, keepdims=False) + gc,
                c, 0),
            g_sp, gs_c)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(), P(batch_axis), P(batch_axis)),
        out_specs=(P(), P(axis), P()),
    )
    def run(stacked_params, outer, inputs, labels):
        sp = jax.tree.map(lambda p: p[0], stacked_params)
        idx = jax.lax.axis_index(axis)
        b = inputs.shape[0]
        assert b % M == 0, f"batch {b} not divisible by {M} microbatches"
        mb_in = inputs.reshape(M, b // M, *inputs.shape[1:])
        mb_lab = labels.reshape(M, b // M, *labels.shape[1:])

        want_axes = (axis,) if batch_axis is None else (axis, batch_axis)

        def _leaf_varying(x):
            # pcast rejects an already-varying operand; consult the
            # aval's varying-manual-axes set and convert only fresh
            # constants (zeros_like of a varying leaf is varying itself).
            # Under a (data, pipe) mesh the buffers must be varying over
            # BOTH axes, or cond branches mixing batch-derived values
            # with carries fail VMA typing.
            for ax in want_axes:
                if ax not in getattr(jax.typeof(x), "vma", frozenset()):
                    x = jax.lax.pcast(x, ax, to="varying")
            return x

        varying = lambda tr: jax.tree.map(_leaf_varying, tr)
        act = jax.eval_shape(embed_fn, outer, mb_in[0])
        # Use fully-VARYING views of the param trees inside the ticks:
        # differentiating w.r.t. a tree that is invariant over any mesh
        # axis makes the vjp transpose insert a psum_invariant INSIDE
        # the cond branch — a collective only some devices execute,
        # which deadlocks the mesh.  With varying params the pullback
        # stays device-local and the psums after the scan combine the
        # contributions (pipe for outer, batch_axis for both).
        outer = varying(outer)
        sp = varying(sp)
        zero_act = varying(jnp.zeros(act.shape, act.dtype))
        zeros_sp = varying(jax.tree.map(jnp.zeros_like, sp))
        zeros_chunk = varying(jax.tree.map(jnp.zeros_like, chunk_tree(sp, 0)))
        zeros_outer = varying(jax.tree.map(jnp.zeros_like, outer))
        f32_0 = varying(jnp.float32(0.0))
        # d(mean over microbatches)/d(l_m); varying like the vjp output
        seed = varying(jnp.float32(1.0 / M))

        def tick(carry, row):
            if zb:
                (h_act, h_cot, ringbuf, cotstash, g_sp, g_out,
                 loss_acc) = carry
                (isf, isb, mfs, mbs, cfs, cbs, sfs, sbs, lfs, lbs,
                 ras, rais, rcs, rcis, isw, mws, cws, sws) = row
            else:
                h_act, h_cot, ringbuf, g_sp, g_out, loss_acc = carry
                (isf, isb, mfs, mbs, cfs, cbs, sfs, sbs, lfs, lbs,
                 ras, rais, rcs, rcis) = row
            f = jnp.take(isf, idx)
            bk = jnp.take(isb, idx)
            mf, mb_ = jnp.take(mfs, idx), jnp.take(mbs, idx)
            cf, cb = jnp.take(cfs, idx), jnp.take(cbs, idx)
            sf, sb = jnp.take(sfs, idx), jnp.take(sbs, idx)
            lf, lb = jnp.take(lfs, idx), jnp.take(lbs, idx)

            # ---- forward tick: (maybe embed) -> stage -> stash input.
            # Buffers are (V, ring, ...) / latches (V, ...): chunk-
            # indexed so interleaved placements keep V streams apart.
            def do_f(_):
                x_in = jax.lax.cond(
                    (idx == 0) & (cf == 0),
                    lambda _: _leaf_varying(
                        embed_fn(outer, jax.lax.dynamic_index_in_dim(
                            mb_in, mf, 0, keepdims=False))),
                    lambda _: jax.lax.dynamic_index_in_dim(
                        h_act, lf, 0, keepdims=False),
                    None,
                )
                y = apply_stage(chunk_tree(sp, cf), x_in, cf * S + idx)
                slab = jax.lax.dynamic_index_in_dim(ringbuf, cf, 0, keepdims=False)
                slab = jax.lax.dynamic_update_index_in_dim(slab, x_in, sf, 0)
                return y, jax.lax.dynamic_update_index_in_dim(ringbuf, slab, cf, 0)

            y_send, ringbuf = jax.lax.cond(
                f, do_f, lambda _: (zero_act, ringbuf), None)

            # one ring-stash read for every backward flavor (joint 1F1B
            # B, zb B, zb W): the zb bit-parity guarantee rests on these
            # reads staying identical across the three consumers
            def stash_ctx(c, s, m):
                slab = jax.lax.dynamic_index_in_dim(
                    ringbuf, c, 0, keepdims=False)
                x_saved = jax.lax.dynamic_index_in_dim(
                    slab, s, 0, keepdims=False)
                lab = jax.lax.dynamic_index_in_dim(
                    mb_lab, m, 0, keepdims=False)
                return x_saved, lab, chunk_tree(sp, c), c * S + idx

            # ---- backward tick(s).  1F1B: ONE joint tick — recompute
            # fwd under vjp from the stashed input, pull param + input
            # grads together.  zb: the B tick pulls ONLY the input
            # cotangent (banking the incoming cotangent at the same
            # m % ring slot for W); the W tick re-runs the SAME vjp on
            # the SAME operands and pulls ONLY the param (+ outer)
            # grads — identical primitives on identical inputs, so
            # every gradient is bit-for-bit the 1F1B value.
            if not zb:
                def do_b(_):
                    x_saved, lab, pc, stage_ix = stash_ctx(cb, sb, mb_)

                    def last(_):
                        def fn(pc_, out_, x_):
                            return head_fn(out_, apply_stage(pc_, x_, stage_ix), lab)

                        l, pull = jax.vjp(fn, pc, outer, x_saved)
                        gs, go, gx = pull(seed)
                        return gs, varying(go), gx, l

                    def inner(_):
                        y, pull = jax.vjp(
                            lambda pc_, x_: apply_stage(pc_, x_, stage_ix),
                            pc, x_saved)
                        gs, gx = pull(jax.lax.dynamic_index_in_dim(
                            h_cot, lb, 0, keepdims=False))
                        return gs, zeros_outer, gx, f32_0

                    gs, go, gx, l = jax.lax.cond(
                        (idx == S - 1) & (cb == V - 1), last, inner, None)

                    def embed_bwd(_):
                        tok = jax.lax.dynamic_index_in_dim(
                            mb_in, mb_, 0, keepdims=False)
                        _, pull = jax.vjp(lambda o: embed_fn(o, tok), outer)
                        (go0,) = pull(gx)
                        return jax.tree.map(jnp.add, go, go0)

                    go = jax.lax.cond(
                        (idx == 0) & (cb == 0), embed_bwd, lambda _: go, None)
                    return gs, go, gx, l

                gs_d, go_d, gx_send, l = jax.lax.cond(
                    bk, do_b,
                    lambda _: (zeros_chunk, zeros_outer, zero_act, f32_0), None)
                g_sp = chunk_scatter_add(g_sp, gs_d, cb)
                g_out = jax.tree.map(jnp.add, g_out, go_d)
                loss_acc = loss_acc + l
            else:
                wk = jnp.take(isw, idx)
                mw = jnp.take(mws, idx)
                cw = jnp.take(cws, idx)
                sw = jnp.take(sws, idx)

                def do_b(_):
                    x_saved, lab, pc, stage_ix = stash_ctx(cb, sb, mb_)

                    def last(_):
                        def fn(pc_, out_, x_):
                            return head_fn(out_, apply_stage(pc_, x_, stage_ix), lab)

                        l, pull = jax.vjp(fn, pc, outer, x_saved)
                        _gs, _go, gx = pull(seed)
                        # W re-derives from the static seed; the stash
                        # write below still happens (dead value)
                        return gx, l, zero_act

                    def inner(_):
                        cot = jax.lax.dynamic_index_in_dim(
                            h_cot, lb, 0, keepdims=False)
                        y, pull = jax.vjp(
                            lambda pc_, x_: apply_stage(pc_, x_, stage_ix),
                            pc, x_saved)
                        _gs, gx = pull(cot)
                        return gx, f32_0, cot

                    gx, l, banked = jax.lax.cond(
                        (idx == S - 1) & (cb == V - 1), last, inner, None)
                    cslab = jax.lax.dynamic_index_in_dim(
                        cotstash, cb, 0, keepdims=False)
                    cslab = jax.lax.dynamic_update_index_in_dim(
                        cslab, banked, sb, 0)
                    return gx, l, jax.lax.dynamic_update_index_in_dim(
                        cotstash, cslab, cb, 0)

                gx_send, l, cotstash = jax.lax.cond(
                    bk, do_b, lambda _: (zero_act, f32_0, cotstash), None)
                loss_acc = loss_acc + l

                def do_w(_):
                    x_saved, lab, pc, stage_ix = stash_ctx(cw, sw, mw)

                    def last(_):
                        def fn(pc_, out_, x_):
                            return head_fn(out_, apply_stage(pc_, x_, stage_ix), lab)

                        _l, pull = jax.vjp(fn, pc, outer, x_saved)
                        gs, go, _gx = pull(seed)
                        return gs, varying(go)

                    def inner(_):
                        cot = jax.lax.dynamic_index_in_dim(
                            jax.lax.dynamic_index_in_dim(
                                cotstash, cw, 0, keepdims=False),
                            sw, 0, keepdims=False)
                        y, pull = jax.vjp(
                            lambda pc_, x_: apply_stage(pc_, x_, stage_ix),
                            pc, x_saved)
                        gs, gx = pull(cot)

                        def embed_bwd(_):
                            tok = jax.lax.dynamic_index_in_dim(
                                mb_in, mw, 0, keepdims=False)
                            _, pull2 = jax.vjp(
                                lambda o: embed_fn(o, tok), outer)
                            (go0,) = pull2(gx)
                            return go0

                        go = jax.lax.cond(
                            (idx == 0) & (cw == 0), embed_bwd,
                            lambda _: zeros_outer, None)
                        return gs, go

                    return jax.lax.cond(
                        (idx == S - 1) & (cw == V - 1), last, inner, None)

                gs_w, go_w = jax.lax.cond(
                    wk, do_w, lambda _: (zeros_chunk, zeros_outer), None)
                g_sp = chunk_scatter_add(g_sp, gs_w, cw)
                g_out = jax.tree.map(jnp.add, g_out, go_w)

            # ---- neighbor transfers + latches (collectives stay
            # OUTSIDE every cond: all devices participate every tick).
            # The barrier serializes the two transfers: XLA gives every
            # manual-mode collective the same channel id, and the CPU
            # thunk executor runs independent collectives concurrently,
            # so without a data dependency the two permutes join each
            # other's rendezvous and deadlock.  Sequential same-channel
            # collectives are safe (each epoch is a full barrier — the
            # same property every scan-over-ppermute pipeline relies on).
            recv_a = jax.lax.ppermute(y_send, axis, fwd_perm)
            gx_send = jax.lax.optimization_barrier((gx_send, recv_a))[0]
            recv_c = jax.lax.ppermute(gx_send, axis, bwd_perm)
            h_act = jnp.where(
                jnp.take(ras, idx),
                jax.lax.dynamic_update_index_in_dim(
                    h_act, recv_a, jnp.take(rais, idx), 0),
                h_act)
            h_cot = jnp.where(
                jnp.take(rcs, idx),
                jax.lax.dynamic_update_index_in_dim(
                    h_cot, recv_c, jnp.take(rcis, idx), 0),
                h_cot)
            if zb:
                return (h_act, h_cot, ringbuf, cotstash, g_sp, g_out,
                        loss_acc), None
            return (h_act, h_cot, ringbuf, g_sp, g_out, loss_acc), None

        latch0 = varying(
            jnp.zeros((V * sched.latch_depth,) + act.shape, act.dtype))
        ringbuf0 = varying(
            jnp.zeros((V, ring) + act.shape, act.dtype))
        if zb:
            # the zb cot stash: one banked cotangent per in-flight
            # microbatch, per chunk — same window the input ring bounds
            carry0 = (latch0, latch0, ringbuf0, ringbuf0, zeros_sp,
                      zeros_outer, f32_0)
            (_, _, _, _, g_sp, g_out, loss_acc), _ = jax.lax.scan(
                tick, carry0, rows)
        else:
            carry0 = (latch0, latch0, ringbuf0, zeros_sp, zeros_outer, f32_0)
            (_, _, _, g_sp, g_out, loss_acc), _ = jax.lax.scan(
                tick, carry0, rows)

        loss = jax.lax.psum(loss_acc, axis) / M
        g_out = jax.lax.psum(g_out, axis)
        if batch_axis is not None:  # DP composition: mean over data rows
            n = mesh.shape[batch_axis]
            loss = jax.lax.psum(loss, batch_axis) / n
            g_out = jax.tree.map(
                lambda g: jax.lax.psum(g, batch_axis) / n, g_out)
            g_sp = jax.tree.map(
                lambda g: jax.lax.psum(g, batch_axis) / n, g_sp)
        return loss, jax.tree.map(lambda g: g[None], g_sp), g_out

    run.schedule = sched
    run.utilization = sched.utilization
    return run


def make_train_step_1f1b(
    stage_fn: Callable,
    embed_fn: Callable,
    head_fn: Callable,
    optimizer: Optimizer,
    mesh: Mesh,
    axis: str = PIPE_AXIS,
    num_microbatches: Optional[int] = None,
    batch_axis: Optional[str] = None,
    interleave: int = 1,
    donate: bool = True,
    input_key: str = "tokens",
    label_key: Optional[str] = None,
    schedule: str = "1f1b",
):
    """Compile a full 1F1B (or zero-bubble, ``schedule="zb"``) training
    step.

    ``TrainState.params`` is the split tree ``{"outer": ..., "stages":
    ...}`` (``lm_pp_1f1b``'s ``split_params`` builds it for the LM).
    Gradients never leave their pipe device except the psum'd outer
    tree, so the optimizer update is stage-local like the GPipe step
    (``pp.make_train_step_pp``).  ``label_key`` defaults to
    ``input_key`` (next-token LM losses read the shifted inputs).
    """
    run = pipeline_grads_1f1b(
        stage_fn, embed_fn, head_fn, mesh, axis=axis,
        num_microbatches=num_microbatches, batch_axis=batch_axis,
        interleave=interleave, schedule=schedule,
    )
    repl = NamedSharding(mesh, P())
    # under DP composition the batch arrives data-sharded (the
    # shard_batch layout), not replicated
    batch_sh = NamedSharding(mesh, P(batch_axis)) if batch_axis else repl
    state_shardings = split_state_shardings(mesh, axis)

    def step(state: TrainState, batch):
        loss, g_stages, g_outer = run(
            state.params["stages"], state.params["outer"],
            batch[input_key], batch[label_key or input_key],
        )
        grads = {"outer": g_outer, "stages": g_stages}
        new_params, new_opt = optimizer.apply(
            state.params, grads, state.opt_state, state.step
        )
        return TrainState(
            params=new_params, opt_state=new_opt,
            model_state=state.model_state, step=state.step + 1,
        ), {"loss": loss}

    def compile_for(state: TrainState):
        sh = state_shardings(state)
        return jax.jit(
            step,
            in_shardings=(sh, batch_sh),
            out_shardings=(sh, repl),
            donate_argnums=(0,) if donate else (),
        )

    return compile_for
