"""Auxiliary parity pieces: ensure_synced debug check, Wandb logger glue."""

import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from fluxdistributed_tpu import mesh as mesh_lib, sharding


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.data_mesh(8)


def test_ensure_synced_passes_on_replicated_state(mesh):
    tree = {"w": np.arange(12, dtype=np.float32).reshape(3, 4), "b": np.ones(4, np.float32)}
    rep = sharding.replicate(tree, mesh)
    assert sharding.ensure_synced(rep)


def test_ensure_synced_catches_divergence(mesh):
    """Hand-build a 'replicated' array whose device copies differ — the
    failure mode the reference's check exists for (src/ddp_tasks.jl:115-126)."""
    devs = list(mesh.devices.flat)
    per_dev = [
        jax.device_put(jnp.full((4,), float(i)), d) for i, d in enumerate(devs)
    ]
    bad = jax.make_array_from_single_device_arrays(
        (4,), NamedSharding(mesh, P()), per_dev
    )
    with pytest.raises(AssertionError, match="replica divergence"):
        sharding.ensure_synced({"x": bad})


def test_wandb_logger_uses_wandb_module(monkeypatch):
    """WandbLogger is the @require-Wandb hook analog
    (src/FluxDistributed.jl:22-24) — exercised against a stub module."""
    calls = {"init": [], "log": []}
    stub = types.ModuleType("wandb")
    stub.init = lambda **kw: calls["init"].append(kw)
    stub.log = lambda metrics, step=None: calls["log"].append((metrics, step))
    monkeypatch.setitem(sys.modules, "wandb", stub)

    from fluxdistributed_tpu.train.logging import WandbLogger

    lg = WandbLogger(project="test-proj")
    lg.log({"loss": 1.5}, step=3)
    assert calls["init"] == [{"project": "test-proj"}]
    assert calls["log"] == [({"loss": 1.5}, 3)]


def test_wandb_logger_pushes_run_config(monkeypatch):
    """config= rides wandb.init at construction (the reference's
    WandbLogger(...; config=...) behavior, src/loggers/wandb.jl:1) and
    log_config merges later additions via run.config.update."""
    calls = {"init": [], "update": []}

    class _Cfg:
        def update(self, d, allow_val_change=False):
            calls["update"].append((d, allow_val_change))

    class _Run:
        config = _Cfg()

    stub = types.ModuleType("wandb")
    stub.init = lambda **kw: (calls["init"].append(kw), _Run())[1]
    stub.log = lambda *a, **kw: None
    monkeypatch.setitem(sys.modules, "wandb", stub)

    from fluxdistributed_tpu.train.logging import WandbLogger

    cfg = {"model": "lm_small", "spmd": "fsdp", "lr": 3e-4, "opt": "adamw"}
    lg = WandbLogger(project="p", config=cfg)
    assert calls["init"] == [{"project": "p", "config": cfg}]
    lg.log_config({"mesh": {"data": 8}})
    assert calls["update"] == [({"mesh": {"data": 8}}, True)]


def test_docs_site_config_complete():
    """mkdocs.yml (the Documenter-site analog, ref docs/make.jl) stays in
    sync with docs/: every nav entry exists, every docs page is in nav."""
    import os

    yaml = pytest.importorskip("yaml")

    root = os.path.join(os.path.dirname(__file__), "..")
    with open(os.path.join(root, "mkdocs.yml")) as f:
        cfg = yaml.safe_load(f)
    nav = {v for item in cfg["nav"] for v in item.values()}
    pages = {f for f in os.listdir(os.path.join(root, "docs")) if f.endswith(".md")}
    assert nav == pages, (nav - pages, pages - nav)
