"""Functional optimizers matching the reference's Optimisers.jl contract.

The reference pins Optimisers.jl to an early revision whose API is
``st = Optimisers.state(opt, model)`` then ``m, st = opt(m, grads, st)``
(reference: src/overloads.jl:1-34 implements exactly those two tree walks;
README.md:37-38 uses ``Momentum(0.01, 0.9)``; src/sync.jl:97 uses
``ADAM()``).  The contract is *functional*: the optimizer is a pure value,
state is an explicit tree, and the update returns new params + new state.

That contract is already the idiomatic JAX shape, so here it is directly:

    opt = momentum(0.01, 0.9)
    state = opt.init(params)
    params, state = opt.apply(params, grads, state, step)

``apply`` is pure and jit-compatible (``step`` may be a traced scalar so
learning-rate schedules compile into the training step).  ``None`` leaves
in the gradient tree (non-differentiable / stateless layers — the
reference's ``nothing`` leaves) leave the corresponding parameter and
state untouched.

Implemented rules (hyperparameter semantics follow Flux/Optimisers.jl
where the reference uses them, standard forms otherwise):

* ``descent(lr)``          — plain SGD
* ``momentum(lr, rho)``    — Flux ``Momentum``: v = ρv + ηg; x -= v
* ``nesterov(lr, rho)``    — Flux ``Nesterov``
* ``adam(lr, b1, b2, eps)``— bias-corrected Adam (``ADAM()`` analog)
* ``adamw(...)``           — Adam + decoupled weight decay
* ``lars(...)``            — layerwise-adaptive momentum for large batch
                             (the ConvNeXt-XL large-batch config in
                             BASELINE.json)

Gradient/parameter transformations (wrap any optimizer):
``clip_by_global_norm(opt, max_norm)`` and ``with_ema(opt, decay)`` /
``ema_params(state)``.

Schedules (callables ``step -> lr``, usable anywhere ``lr`` is accepted):
``constant``, ``step_decay``, ``cosine_decay``, ``warmup_cosine``.
``step_decay(lr0, 0.2, 10)`` reproduces the reference's legacy LR/5 every
10 cycles (src/test.jl:50).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Union

import jax
import jax.numpy as jnp

Pytree = Any
Schedule = Callable[[Any], Any]
LR = Union[float, Schedule]

__all__ = [
    "Optimizer",
    "descent",
    "momentum",
    "nesterov",
    "adam",
    "adamw",
    "lars",
    "global_norm",
    "clip_by_global_norm",
    "with_ema",
    "ema_params",
    "constant",
    "step_decay",
    "cosine_decay",
    "warmup_cosine",
]


def _is_none(x):
    return x is None


def _lr_at(lr: LR, step):
    return lr(step) if callable(lr) else lr


def _map(f, *trees):
    """tree.map over grad trees where ``None`` marks a frozen leaf."""
    return jax.tree.map(f, *trees, is_leaf=_is_none)


def _map_with_state(step_leaf, params, state, grads):
    """Apply ``step_leaf(p, s, g) -> (p', s')`` across the three trees,
    tolerating ``None`` grad leaves and per-leaf state of any shape
    (e.g. Adam's ``(m, v)`` pairs, which a naive tree.map would descend
    into)."""
    flat_p, treedef = jax.tree.flatten(params, is_leaf=_is_none)
    flat_s = treedef.flatten_up_to(state)
    flat_g = treedef.flatten_up_to(grads)
    out = [step_leaf(p, s, g) for p, s, g in zip(flat_p, flat_s, flat_g)]
    new_p = treedef.unflatten([o[0] for o in out])
    new_s = treedef.unflatten([o[1] for o in out])
    return new_p, new_s


@dataclasses.dataclass(frozen=True)
class Optimizer:
    """A pure optimizer: ``init(params) -> state``;
    ``apply(params, grads, state, step) -> (params, state)``."""

    init: Callable[[Pytree], Pytree]
    update: Callable[[Pytree, Pytree, Pytree, Any], tuple[Pytree, Pytree]]
    name: str = "optimizer"

    def apply(self, params: Pytree, grads: Pytree, state: Pytree, step=0):
        return self.update(params, grads, state, step)

    # Allow the reference's call syntax: ``m, st = opt(m, grads, st)``
    # (src/overloads.jl:1-12).
    def __call__(self, params: Pytree, grads: Pytree, state: Pytree, step=0):
        return self.update(params, grads, state, step)


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


def descent(lr: LR = 0.1) -> Optimizer:
    """Plain gradient descent: ``x -= η g``."""

    def init(params):
        return _map(lambda p: None, params)

    def update(params, grads, state, step):
        eta = _lr_at(lr, step)

        def f(p, g):
            return p if g is None else p - eta * g

        return _map(f, params, grads), state

    return Optimizer(init, update, "descent")


def momentum(lr: LR = 0.01, rho: float = 0.9) -> Optimizer:
    """Flux ``Momentum(η, ρ)``: ``v = ρ v + η g; x -= v``.

    The reference's demo optimizer (README.md:37-38).
    """

    def init(params):
        return _map(lambda p: None if p is None else jnp.zeros_like(p), params)

    def update(params, grads, state, step):
        eta = _lr_at(lr, step)

        def fv(v, g):
            return v if g is None else rho * v + eta * g

        def fp(p, v, g):
            return p if g is None else p - v

        new_v = _map(fv, state, grads)
        return _map(fp, params, new_v, grads), new_v

    return Optimizer(init, update, "momentum")


def nesterov(lr: LR = 0.01, rho: float = 0.9) -> Optimizer:
    """Flux ``Nesterov(η, ρ)`` lookahead momentum."""

    def init(params):
        return _map(lambda p: None if p is None else jnp.zeros_like(p), params)

    def update(params, grads, state, step):
        eta = _lr_at(lr, step)

        def step_leaf(p, v, g):
            if g is None:
                return p, v
            v2 = rho * v - eta * g
            d = rho * rho * v - (1 + rho) * eta * g
            return p + d, v2

        return _map_with_state(step_leaf, params, state, grads)

    return Optimizer(init, update, "nesterov")


def adam(lr: LR = 1e-3, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8) -> Optimizer:
    """Bias-corrected Adam — the ``ADAM()`` analog (src/sync.jl:97)."""

    def init(params):
        def f(p):
            if p is None:
                return None
            return (jnp.zeros_like(p), jnp.zeros_like(p))

        return _map(f, params)

    def update(params, grads, state, step):
        eta = _lr_at(lr, step)
        t = jnp.asarray(step, jnp.float32) + 1.0
        c1 = 1.0 - jnp.power(b1, t)
        c2 = 1.0 - jnp.power(b2, t)

        def step_leaf(p, mv, g):
            if g is None:
                return p, mv
            m, v = mv
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * (g * g)
            mhat = m / c1
            vhat = v / c2
            return p - eta * mhat / (jnp.sqrt(vhat) + eps), (m, v)

        return _map_with_state(step_leaf, params, state, grads)

    return Optimizer(init, update, "adam")


def adamw(
    lr: LR = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 1e-2,
) -> Optimizer:
    """Adam with decoupled weight decay (for the ViT/ConvNeXt configs)."""
    base = adam(lr, b1, b2, eps)

    def update(params, grads, state, step):
        eta = _lr_at(lr, step)
        new_p, new_s = base.update(params, grads, state, step)

        def decay(np_, p, g):
            return np_ if g is None else np_ - eta * weight_decay * p

        return _map(decay, new_p, params, grads), new_s

    return Optimizer(base.init, update, "adamw")


def lars(
    lr: LR = 1.0,
    momentum_coef: float = 0.9,
    weight_decay: float = 0.0,
    trust_coefficient: float = 1e-3,
    eps: float = 1e-9,
) -> Optimizer:
    """LARS — layerwise adaptive rate scaling for large-batch training
    (the ConvNeXt-XL / ImageNet-21k large-batch config, BASELINE.json)."""

    def init(params):
        return _map(lambda p: None if p is None else jnp.zeros_like(p), params)

    def update(params, grads, state, step):
        eta = _lr_at(lr, step)

        def step_leaf(p, v, g):
            if g is None:
                return p, v
            g = g + weight_decay * p
            p_norm = jnp.linalg.norm(p.reshape(-1))
            g_norm = jnp.linalg.norm(g.reshape(-1))
            trust = jnp.where(
                (p_norm > 0) & (g_norm > 0),
                trust_coefficient * p_norm / (g_norm + eps),
                1.0,
            )
            v2 = momentum_coef * v + eta * trust * g
            return p - v2, v2

        return _map_with_state(step_leaf, params, state, grads)

    return Optimizer(init, update, "lars")


# ---------------------------------------------------------------------------
# Gradient transformations
# ---------------------------------------------------------------------------


def global_norm(tree: Pytree):
    """L2 norm over every non-``None`` leaf of a gradient tree (f32
    accumulation regardless of leaf dtype)."""
    leaves = [g for g in jax.tree.leaves(tree, is_leaf=_is_none) if g is not None]
    if not leaves:
        return jnp.zeros(())
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    )


def clip_by_global_norm(optimizer: Optimizer, max_norm: float) -> Optimizer:
    """Wrap an optimizer with global-norm gradient clipping (the standard
    transformer-training guard; ViT/ConvNeXt recipes clip at 1.0).

    Pure and jit-compatible: grads whose global norm exceeds
    ``max_norm`` are rescaled to exactly ``max_norm`` before the wrapped
    rule runs; smaller gradients pass through untouched.  ``None``
    (frozen) leaves are preserved.
    """

    def update(params, grads, state, step):
        norm = global_norm(grads)
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))

        def f(g):
            return None if g is None else (g * scale).astype(g.dtype)

        return optimizer.update(params, _map(f, grads), state, step)

    return Optimizer(
        init=optimizer.init, update=update, name=f"clip{max_norm}({optimizer.name})"
    )


def with_ema(optimizer: Optimizer, decay: float = 0.9999) -> Optimizer:
    """Track an exponential moving average of the parameters alongside
    any optimizer (the ViT/ConvNeXt eval-quality standard).

    The shadow copy lives inside the optimizer state (so it rides
    checkpointing, replication, and donation for free); read it with
    ``ema_params(opt_state)`` and evaluate via e.g.
    ``dataclasses.replace(state, params=ema_params(state.opt_state))``.
    The decay is warmup-corrected (``min(decay, (1+t)/(10+t))``) so early
    steps don't average against the random init.

    State layout honors the opt-state contract the TP/PP sharding
    machinery assumes (tp.state_specs/broadcast_prefix: "mirror the
    param tree, extra structure nested PER PARAM"): each param leaf maps
    to ``{"inner": <wrapped state leaf>, "ema": <shadow leaf>}``.  The
    shadow is a real copy (never an alias of the live param buffer, so
    donation can't free one array through two leaves) and stays in the
    param dtype.
    """

    def _split(params, state):
        """state tree -> (ema tree, inner tree, treedef, flat params)."""
        flat_p, treedef = jax.tree.flatten(params, is_leaf=_is_none)
        flat_s = treedef.flatten_up_to(state)
        inner = treedef.unflatten(
            [None if s is None else s["inner"] for s in flat_s]
        )
        ema = treedef.unflatten([None if s is None else s["ema"] for s in flat_s])
        return ema, inner, treedef, flat_p

    def _join(treedef, params_flat, inner, ema):
        flat_i = treedef.flatten_up_to(inner)
        flat_e = treedef.flatten_up_to(ema)
        return treedef.unflatten(
            [
                None if p is None else {"inner": i, "ema": e}
                for p, i, e in zip(params_flat, flat_i, flat_e)
            ]
        )

    def init(params):
        inner = optimizer.init(params)
        ema = _map(lambda p: None if p is None else jnp.copy(p), params)
        flat_p, treedef = jax.tree.flatten(params, is_leaf=_is_none)
        return _join(treedef, flat_p, inner, ema)

    def update(params, grads, state, step):
        ema, inner, treedef, flat_p = _split(params, state)
        new_p, new_inner = optimizer.update(params, grads, inner, step)
        t = jnp.asarray(step, jnp.float32)
        d = jnp.minimum(decay, (1.0 + t) / (10.0 + t))

        def f(e, p):
            if e is None:
                return None
            return (d * e + (1.0 - d) * p).astype(p.dtype)

        new_ema = _map(f, ema, new_p)
        return new_p, _join(treedef, flat_p, new_inner, new_ema)

    return Optimizer(init=init, update=update, name=f"ema{decay}({optimizer.name})")


def ema_params(opt_state: Pytree) -> Pytree:
    """The EMA shadow parameters from a ``with_ema`` optimizer state."""

    def _is_slot(x):
        return x is None or (isinstance(x, dict) and set(x) == {"inner", "ema"})

    leaves, treedef = jax.tree.flatten(opt_state, is_leaf=_is_slot)
    if not any(isinstance(s, dict) and "ema" in s for s in leaves):
        raise ValueError("opt_state does not carry an EMA (use optim.with_ema)")
    return treedef.unflatten([None if s is None else s["ema"] for s in leaves])


# ---------------------------------------------------------------------------
# Learning-rate schedules
# ---------------------------------------------------------------------------


def constant(lr: float) -> Schedule:
    return lambda step: lr


def step_decay(lr0: float, factor: float = 0.2, every: int = 10) -> Schedule:
    """Multiply the LR by ``factor`` every ``every`` steps.

    ``step_decay(lr, 0.2, 10)`` is the reference's legacy schedule — LR/5
    every 10 cycles (src/test.jl:50).
    """

    def sched(step):
        k = jnp.floor(jnp.asarray(step, jnp.float32) / every)
        return lr0 * jnp.power(factor, k)

    return sched


def cosine_decay(lr0: float, total_steps: int, final_fraction: float = 0.0) -> Schedule:
    def sched(step):
        t = jnp.clip(jnp.asarray(step, jnp.float32) / max(total_steps, 1), 0.0, 1.0)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * t))
        return lr0 * (final_fraction + (1.0 - final_fraction) * cos)

    return sched


def warmup_cosine(lr0: float, warmup_steps: int, total_steps: int) -> Schedule:
    cos = cosine_decay(lr0, max(total_steps - warmup_steps, 1))

    def sched(step):
        s = jnp.asarray(step, jnp.float32)
        warm = lr0 * s / max(warmup_steps, 1)
        return jnp.where(s < warmup_steps, warm, cos(s - warmup_steps))

    return sched
