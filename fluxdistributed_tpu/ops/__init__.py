from .losses import logitcrossentropy, crossentropy, mse
from .metrics import topkaccuracy, onehot, showpreds
from .attention import attention_core, blockwise_attention, dot_product_attention

__all__ = [
    "logitcrossentropy",
    "crossentropy",
    "mse",
    "topkaccuracy",
    "onehot",
    "showpreds",
    "dot_product_attention",
    "blockwise_attention",
    "attention_core",
    # Pallas kernels (lazy: importing the package must not pay for
    # jax.experimental.pallas unless a kernel is actually used)
    "flash_attention",
    "flash_attention_lse",
    "flash_decode",
    "flash_decode_paged",
]

_LAZY = {
    "flash_attention": "pallas_attention",
    "flash_attention_lse": "pallas_attention",
    "flash_decode": "pallas_decode",
    "flash_decode_paged": "pallas_decode",
}


def __getattr__(name):  # PEP 562
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
