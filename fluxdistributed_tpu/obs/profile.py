"""Per-layer / per-stage cost profiles: static jaxpr costs + measured
phase wall-times, persisted as a versioned topology-fingerprinted JSON
artifact.

ROADMAP item 4's pipeline planner needs exactly two inputs nothing in
the repo persisted until now: *what does each layer cost* (to place
stage boundaries) and *what did the schedule actually spend* (to check
the placement).  This module is that data layer:

* **static costs** — FLOPs / bytes-accessed from XLA's own
  ``cost_analysis`` on the staged-out (lowered, never compiled)
  program.  :func:`lm_layer_costs` isolates the per-decoder-block cost
  with a depth-difference: a homogeneous stack's cost is affine in
  depth, so ``cost(depth=2) - cost(depth=1)`` is one block and the
  remainder is the embed + head "outer" cost.  :func:`step_cost` prices
  any prepared train step (the REAL ``prepare_training`` output), and
  :func:`variant_costs` sweeps the registered parallelism variants
  through ``analysis/variants.py`` — the same builders fdtpu-lint's
  jaxpr layer checks.
* **measured wall-times** — the span/phase histograms instrumented runs
  already feed (``fdtpu_train_phase_seconds`` et al.), lifted out of a
  registry with full bucket detail so offline consumers can recompute
  any percentile via :func:`..obs.metrics.bucket_percentile`.

The artifact (:class:`Profile`) carries a ``schema`` tag, the
:func:`..compilation.topology_fingerprint` digest plus a human-readable
topology block, and rejects cross-topology reuse at load time
(:meth:`Profile.verify` raises :class:`ProfileMismatch`): a profile
measured on 8 CPU devices must never silently drive stage placement on
a v5e slice.

Schema (``fdtpu-profile/v2`` — v1 artifacts still load; the additive
``memory`` and ``comms`` sections simply read empty)::

    {"schema": "fdtpu-profile/v2", "created_unix": ...,
     "fingerprint": "<16-hex topology digest>",
     "topology": {"jax", "platform", "device_kind",
                  "device_count", "process_count", "mesh"},
     "static": {"model": {"batch", "seqlen", "depth",
                          "block": {"flops", "bytes"},
                          "outer": {"flops", "bytes"},
                          "total": {"flops", "bytes"}} | null,
                "step":  {"flops", "bytes"} | null,
                "variants": {name: {"flops", "bytes"}}},
     "memory": {"state": {"param_bytes", "opt_state_bytes",
                          "model_state_bytes", "total_bytes"},
                "step": {"argument_bytes", "output_bytes",
                         "temp_bytes", "alias_bytes",
                         "generated_code_bytes",
                         "peak_bytes"} | null,   # memory_analysis
                "variants": {name: {...}}},      # bin/fit.py sweeps
     "comms": {"step": {"jaxpr": [...], "hlo": [...]},  # obs.comms
               "variants": {name: {...}}},
     "measured": {"phases": {phase: {"sum", "count",
                                     "bounds", "counts"}},
                  "step_seconds": {...}, "counters": {...},
                  "hbm": {...},               # live memory_stats peak
                  "pp_rows": [...]},          # pp_bubble.py runs only
     "meta": {...}}

Consumers today: ``benchmarks/pp_bubble.py`` (modeled-vs-measured
bubble accounting via :func:`bubble_report`), ``bin/driver.py
--profile-out`` (trainer runs), the profile-guided stage partitioner
(docs/parallelism.md), and ``bin/fit.py`` — the memory/comms fit
checker that ranks variants by HBM headroom on a topology.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .metrics import Histogram, Registry, get_registry

__all__ = [
    "Profile",
    "ProfileMismatch",
    "bubble_report",
    "collect_profile",
    "describe_topology",
    "lm_layer_costs",
    "measured_from_registry",
    "step_cost",
    "variant_costs",
]

SCHEMA = "fdtpu-profile/v2"
#: schemas ``Profile.load`` accepts: v1 artifacts predate the memory /
#: comms sections (purely additive — every v1 key means the same thing
#: in v2), so planners and replay tools keep reading them
ACCEPTED_SCHEMAS = ("fdtpu-profile/v1", SCHEMA)


class ProfileMismatch(ValueError):
    """A profile artifact's topology fingerprint does not match the
    consuming process — its costs describe DIFFERENT hardware."""


def describe_topology(mesh=None) -> dict:
    """Human-readable sibling of the opaque fingerprint digest, stored
    alongside it so a rejected artifact can say WHAT differed."""
    import jax

    dev = jax.devices()[0]
    out = {
        "jax": jax.__version__,
        "platform": dev.platform,
        "device_kind": str(getattr(dev, "device_kind", "")),
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
    }
    if mesh is not None:
        out["mesh"] = {k: int(v) for k, v in dict(mesh.shape).items()}
    return out


def _normalize_cost(ca) -> Optional[dict]:
    """``cost_analysis`` returns a dict on this jax, a one-element list
    of dicts on others, and occasionally None (backend without a cost
    model) — normalize to ``{"flops", "bytes"}`` floats or None."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes": float(ca.get("bytes accessed", 0.0)),
    }


def step_cost(fn, args: Tuple[Any, ...]) -> Optional[dict]:
    """FLOPs/bytes of one jit-wrapped program at these arguments via
    ``lower(...).cost_analysis()`` — staging only, nothing compiles.
    Returns None when the callable cannot lower (AOT-deserialized
    executables, strict-check wrappers): a missing static cost must
    degrade the artifact, not kill the run that produced it."""
    try:
        return _normalize_cost(fn.lower(*args).cost_analysis())
    except Exception:  # noqa: BLE001 — any non-lowerable fn is a None
        return None


def _model_forward_cost(model, tokens_shape) -> Optional[dict]:
    import jax
    import jax.numpy as jnp

    toks = jax.ShapeDtypeStruct(tuple(tokens_shape), jnp.int32)
    variables = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, tokens_shape[1]), jnp.int32), train=False))
    low = jax.jit(
        lambda v, t: model.apply(v, t, train=False)).lower(variables, toks)
    return _normalize_cost(low.cost_analysis())


def lm_layer_costs(model, batch_size: int, seqlen: int) -> Optional[dict]:
    """Per-decoder-block and outer (embed + head) forward cost of a
    :class:`~..models.transformer_lm.TransformerLM` at ``(batch_size,
    seqlen)``, via the depth-difference on the staged-out model: the
    stack is homogeneous, so ``cost(d=2) - cost(d=1)`` isolates one
    block exactly and needs no model surgery.  Returns None when the
    model cannot lower standalone (e.g. a mesh-bound moe_fn outside its
    mesh)."""
    depth = int(getattr(model, "depth", 0))
    if depth < 1:
        return None
    try:
        c1 = _model_forward_cost(model.clone(depth=1),
                                 (batch_size, seqlen))
        c2 = _model_forward_cost(model.clone(depth=2),
                                 (batch_size, seqlen))
    except Exception:  # noqa: BLE001 — profile collection is best-effort
        return None
    if c1 is None or c2 is None:
        return None
    block = {k: max(c2[k] - c1[k], 0.0) for k in c1}
    outer = {k: max(c1[k] - block[k], 0.0) for k in c1}
    return {
        "batch": int(batch_size),
        "seqlen": int(seqlen),
        "depth": depth,
        "block": block,
        "outer": outer,
        "total": {k: outer[k] + depth * block[k] for k in block},
    }


def variant_costs(names: Optional[Sequence[str]] = None) -> Dict[str, dict]:
    """Static step cost of every registered parallelism/serve variant,
    built through the REAL ``prepare_training`` / ``LMEngine`` paths in
    :mod:`..analysis.variants` — the same targets the lint suite's
    jaxpr layer sweeps, so what gets priced is exactly what a real run
    compiles.  Expensive (builds each variant on the virtual mesh);
    meant for offline artifact generation, not hot paths."""
    from ..analysis.variants import build_variants

    return {v.name: step_cost(v.fn, v.args) for v in build_variants(names)}


def measured_from_registry(registry: Optional[Registry] = None) -> dict:
    """Lift the measured side out of a metrics registry: the per-phase
    histogram with full bucket detail, the per-item step histogram, and
    the headline counters.  Zero-risk read — snapshots only."""
    reg = registry or get_registry()
    out: dict = {}
    ph = reg.get("fdtpu_train_phase_seconds")
    if isinstance(ph, Histogram):
        out["phases"] = {lv[0]: cell for lv, cell in ph.series().items()
                         if lv and cell["count"]}
    st = reg.get("fdtpu_train_step_seconds")
    if isinstance(st, Histogram):
        cell = st.series().get(())
        if cell is not None and cell["count"]:
            out["step_seconds"] = cell
    counters = {}
    for name in ("fdtpu_train_steps_total", "fdtpu_train_oom_skipped_total",
                 "fdtpu_jax_compiles_total",
                 "fdtpu_jax_compile_seconds_total"):
        v = reg.value(name)
        if v:
            counters[name] = v
    if counters:
        out["counters"] = counters
    return out


@dataclasses.dataclass
class Profile:
    """The versioned cost-profile artifact (schema in the module doc)."""

    fingerprint: str
    topology: dict = dataclasses.field(default_factory=dict)
    static: dict = dataclasses.field(default_factory=dict)
    #: static memory model (state/step/variants — obs.memstats); empty
    #: on v1 artifacts
    memory: dict = dataclasses.field(default_factory=dict)
    #: collective-traffic ledger (step/variants — obs.comms); empty on
    #: v1 artifacts
    comms: dict = dataclasses.field(default_factory=dict)
    measured: dict = dataclasses.field(default_factory=dict)
    meta: dict = dataclasses.field(default_factory=dict)
    schema: str = SCHEMA
    created_unix: float = 0.0

    # -- persistence ---------------------------------------------------
    def save(self, path: str) -> str:
        """Write the artifact (write-then-rename so a cut-short run
        never leaves a half-written JSON a planner could half-read)."""
        doc = dataclasses.asdict(self)
        doc["created_unix"] = self.created_unix or time.time()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "Profile":
        with open(path) as f:
            doc = json.load(f)
        schema = doc.get("schema")
        if schema not in ACCEPTED_SCHEMAS:
            raise ValueError(
                f"{path}: not a {'/'.join(ACCEPTED_SCHEMAS)} artifact "
                f"(schema={schema!r}) — regenerate it with this repo's "
                "profiler")
        fields = {f.name for f in dataclasses.fields(cls)}
        prof = cls(**{k: v for k, v in doc.items() if k in fields})
        # a loaded artifact keeps its recorded schema tag (a v1 doc
        # re-saved without re-collection must not masquerade as v2)
        prof.schema = schema
        return prof

    # -- topology gate -------------------------------------------------
    def verify(self, mesh=None, tag: str = "") -> "Profile":
        """Raise :class:`ProfileMismatch` unless this artifact was
        recorded on THE topology the calling process runs on (same
        fingerprint recipe as the AOT executable keys: jax/jaxlib
        versions, platform, device kind and counts, mesh shape, tag).
        Returns self so loads chain: ``Profile.load(p).verify(mesh)``."""
        from ..compilation import topology_fingerprint

        current = topology_fingerprint(mesh=mesh, tag=tag)
        if current != self.fingerprint:
            raise ProfileMismatch(
                f"profile fingerprint {self.fingerprint} does not match "
                f"this process ({current}): artifact topology "
                f"{self.topology} vs current {describe_topology(mesh)} — "
                "cost profiles do not transfer across topologies; "
                "re-collect on this one")
        return self


def _step_compile_is_cheap() -> bool:
    """Whether re-compiling the step for ``memory_analysis`` is
    acceptable at artifact-collection time: always on CPU; on an
    accelerator only when jax's persistent compilation cache is
    configured (the recompile then hits — or seeds — the cache instead
    of burning minutes)."""
    import jax

    if jax.default_backend() == "cpu":
        return True
    try:
        return bool(jax.config.jax_compilation_cache_dir)
    except AttributeError:  # knob-less build: no cache to absorb it
        return False


def collect_profile(task=None, registry: Optional[Registry] = None,
                    batch=None, meta: Optional[dict] = None) -> Profile:
    """Build a :class:`Profile` from a prepared/finished training task:
    topology from the task's mesh, static costs from the staged-out
    model and the REAL compiled step (both best-effort — a wrapper that
    cannot lower degrades to null, never raises), measured data from
    the registry's phase histograms.  ``batch`` supplies the argument
    shapes for the step cost and (for token batches) the layer costs;
    the trainer passes its last live batch."""
    from ..compilation import topology_fingerprint

    mesh = getattr(task, "mesh", None)
    prof = Profile(
        fingerprint=topology_fingerprint(mesh=mesh),
        topology=describe_topology(mesh),
        measured=measured_from_registry(registry),
        meta=dict(meta or {}),
    )
    static: dict = {"model": None, "step": None, "variants": {}}
    model = getattr(task, "model", None)
    if model is not None:
        prof.meta.setdefault("model", type(model).__name__)
    tokens = batch.get("tokens") if isinstance(batch, dict) else None
    if model is not None and tokens is not None:
        shape = tuple(getattr(tokens, "shape", ()))
        if len(shape) >= 2:
            # device-loop items stack K batches; the per-step shape is
            # the trailing two dims either way
            static["model"] = lm_layer_costs(model, shape[-2], shape[-1])
    if task is not None and batch is not None:
        static["step"] = step_cost(task.step_fn, (task.state, batch))
    prof.static = static
    # -- v2 sections: the memory model and the collective ledger of the
    # REAL step this run compiled, plus the live HBM peak.  All
    # best-effort: every piece degrades to null/empty independently
    # (knob-less jax builds, non-lowerable wrappers, CPU memory_stats)
    from . import comms as comms_lib
    from . import memstats

    memory: dict = {"state": None, "step": None, "variants": {}}
    comms: dict = {"step": {}, "variants": {}}
    if task is not None:
        try:
            memory["state"] = memstats.state_bytes(task.state)
        except Exception:  # noqa: BLE001 — exotic state trees degrade
            pass
    if task is not None and batch is not None:
        args = (task.state, batch)
        try:
            comms["step"]["jaxpr"] = comms_lib.jaxpr_collectives(
                task.step_fn, args)
        except Exception:  # noqa: BLE001 — non-traceable wrappers
            pass
        # memory_analysis / post-opt HLO need a COMPILED program, and
        # lower().compile() here cannot reuse the executable the jit
        # call already built — it is a real second XLA compile.  On CPU
        # that is cheap; on an accelerator it is only acceptable when
        # the persistent compilation cache will absorb it (and populate
        # itself for the next run).  Without the cache, skip: a
        # finished TPU run must not pay minutes of recompile for an
        # optional artifact section.
        compiled = None
        if _step_compile_is_cheap():
            try:
                compiled = task.step_fn.lower(*args).compile()
            except Exception:  # noqa: BLE001 — AOT/strict-check wrappers
                compiled = None
        else:
            memory["step_note"] = (
                "step memory_analysis skipped: recompiling on this "
                "backend without a persistent compilation cache costs "
                "a full XLA compile — enable "
                "compilation.enable_persistent_cache (driver "
                "--compile-cache) to collect it")
        if compiled is not None:
            memory["step"] = memstats.step_memory(
                task.step_fn, args, compiled=compiled)
            try:
                comms["step"]["hlo"] = comms_lib.hlo_collectives(
                    compiled, mesh=mesh)
            except Exception:  # noqa: BLE001
                pass
    prof.memory = memory
    prof.comms = comms
    hbm = memstats.hbm_summary()
    if hbm.get("available"):
        prof.measured["hbm"] = hbm
    return prof


# -- modeled vs measured bubble accounting ---------------------------------

def modeled_bubble(stage_costs: Sequence[float], num_microbatches: int,
                   schedule: str = "1f1b") -> float:
    """Pipeline bubble fraction the schedule model predicts for these
    per-stage costs: steady state is bottlenecked by the most expensive
    stage, fill+drain add S-1 of its ticks, so utilization is
    ``M * mean(stage) / ((M + S - 1) * max(stage))`` and the bubble is
    one minus that.  Uniform stages reduce it to the classic
    ``(S-1)/(M+S-1)``.

    ``schedule="zb"`` applies the ZB-H1 accounting (arXiv:2401.10241's
    handcrafted variant, the form ``pp_1f1b``'s zero-bubble schedule
    implements): the backward is split into input-grad (B) and
    weight-grad (W) halves and W — which depends on nothing downstream —
    fills the drain, shrinking the fill/drain term from
    ``(S-1)·(t_F + t_B_full)`` to ``(S-1)·(t_F + t_B − t_W)``.  With the
    recompute-from-ring cost split F:B:W ≈ 1:1:1 that is one third of
    the 1F1B term, so uniform stages reduce to ``(S-1)/(3M + S-1)``."""
    S = len(stage_costs)
    if S < 1:
        return 0.0
    mx = max(stage_costs)
    if mx <= 0:
        return 0.0
    mean = sum(stage_costs) / S
    M = num_microbatches
    drain = (S - 1) / 3.0 if schedule == "zb" else float(S - 1)
    return 1.0 - (M * mean) / ((M + drain) * mx)


def stage_costs_from_static(model_costs: dict, S: int,
                            boundaries: Optional[Sequence[int]] = None,
                            ) -> List[float]:
    """Split a profile's per-layer static costs into S contiguous stage
    cost sums.  Default placement is the way ``lm_pp`` places them:
    ``depth`` uniform blocks dealt round-floor with the remainder on the
    leading stages; pass a planner's ``boundaries`` (S+1 cut points) to
    model a non-uniform split instead.  The outer (embed + head) cost is
    split between first and last stage either way, and an explicit
    ``static.model.blocks`` per-block list (skewed producers) takes
    precedence over the homogeneous depth-difference ``block`` cost."""
    from ..parallel.pp_plan import stage_costs_for, uniform_boundaries

    depth = int(model_costs["depth"])
    blocks = model_costs.get("blocks")
    if blocks:
        block_costs = [float(b["flops"]) for b in blocks]
    else:
        block_costs = [float(model_costs["block"]["flops"])] * depth
    outer = float(model_costs["outer"]["flops"])
    if boundaries is None:
        boundaries = uniform_boundaries(depth, S)
    return list(stage_costs_for(block_costs, boundaries,
                                (outer / 2, outer / 2)))


def bubble_report(profile: Profile) -> List[dict]:
    """Modeled-vs-measured bubble fractions from a pp_bubble artifact.

    Measured side: the stored rows time the whole fwd+bwd at several M,
    so a least-squares fit ``t_step(M) = a·M + b`` separates the
    per-microbatch steady cost ``a`` from the fixed fill/drain/dispatch
    cost ``b``; each row's measured bubble is the fixed share of its
    own wall time, ``1 - a·M / t_meas``.  (On a real multi-chip slice
    that IS idle-device time; on the shared-core CPU mesh — where
    devices are never idle — it reads the schedule's fixed overhead
    fraction, the honest analog.)  Modeled side: per-stage static costs
    (:func:`stage_costs_from_static` when the artifact has layer costs,
    uniform stages otherwise) through :func:`modeled_bubble`.
    """
    rows = profile.measured.get("pp_rows") or []
    if len(rows) < 2:
        raise ValueError(
            "bubble accounting needs >= 2 measured M rows in the "
            "artifact (run benchmarks/pp_bubble.py --profile-out first)")
    # rows may mix configurations (uniform vs planned splits, 1f1b vs
    # zb) — the linear fit only makes sense within one configuration,
    # so group on the row tags (absent tags = the artifact's single
    # pre-planner configuration, one group)
    default_sched = (profile.meta or {}).get("schedule")
    groups: Dict[tuple, list] = {}
    for r in rows:
        key = (r.get("schedule", default_sched),
               tuple(r["boundaries"]) if r.get("boundaries") else None)
        groups.setdefault(key, []).append(r)
    model_costs = (profile.static or {}).get("model")
    out = []
    for (sched, bounds), grp in groups.items():
        if len(grp) < 2:
            raise ValueError(
                f"bubble accounting needs >= 2 measured M rows per "
                f"configuration; (schedule={sched}, boundaries={bounds}) "
                "has one — extend the M sweep")
        ms = [float(r["M"]) for r in grp]
        ts = [float(r["step_ms"]) for r in grp]
        n = len(grp)
        mean_m, mean_t = sum(ms) / n, sum(ts) / n
        denom = sum((m - mean_m) ** 2 for m in ms)
        a = (sum((m - mean_m) * (t - mean_t)
                 for m, t in zip(ms, ts)) / denom if denom else 0.0)
        b = mean_t - a * mean_m
        for r, t in zip(grp, ts):
            S, M = int(r["S"]), int(r["M"])
            stages = (stage_costs_from_static(model_costs, S,
                                              boundaries=bounds)
                      if model_costs else [1.0] * S)
            measured = (min(max(1.0 - (a * M) / t, 0.0), 1.0)
                        if t > 0 else 0.0)
            row = {
                "M": M, "S": S,
                "step_ms": round(t, 2),
                "modeled_bubble": round(
                    modeled_bubble(
                        stages, M,
                        schedule="zb" if sched == "zb" else "1f1b"), 4),
                "measured_bubble": round(measured, 4),
                "fit_ms_per_microbatch": round(a, 4),
                "fit_fixed_ms": round(b, 4),
            }
            if sched is not None:
                row["schedule"] = sched
            if bounds is not None:
                row["boundaries"] = list(bounds)
            out.append(row)
    return out
