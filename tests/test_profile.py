"""Cost-profile artifacts (obs.profile) + the shared percentile helper.

Fast tier throughout: static costs come from LOWERED (never compiled)
programs, the artifact round-trip is pure JSON, and the bubble-report
math runs on synthetic profiles.  The end-to-end trainer → artifact
path rides the existing obs-integration smoke run
(tests/test_obs_integration.py) so no extra compile is paid here.
"""

from __future__ import annotations

import json
import math

import pytest

from fluxdistributed_tpu import mesh as mesh_lib
from fluxdistributed_tpu.obs import Registry, bucket_percentile
from fluxdistributed_tpu.obs.profile import (
    Profile,
    ProfileMismatch,
    bubble_report,
    collect_profile,
    lm_layer_costs,
    measured_from_registry,
    modeled_bubble,
    stage_costs_from_static,
    step_cost,
)


# ---------------------------------------------------------------------------
# bucket_percentile: the ONE shared percentile implementation
# ---------------------------------------------------------------------------

def test_bucket_percentile_interpolates():
    bounds = (0.1, 1.0, 10.0)
    counts = [10, 10, 0, 0]  # 10 in (0,0.1], 10 in (0.1,1], none beyond
    assert bucket_percentile(bounds, counts, 50) == pytest.approx(0.1)
    # p75 = rank 15 -> halfway through the (0.1, 1] bucket
    assert bucket_percentile(bounds, counts, 75) == pytest.approx(0.55)
    assert bucket_percentile(bounds, counts, 100) == pytest.approx(1.0)


def test_bucket_percentile_edge_cases():
    bounds = (1.0, 2.0)
    assert math.isnan(bucket_percentile(bounds, [0, 0, 0], 50))  # empty
    # all mass in +Inf: the honest answer is the largest finite bound
    assert bucket_percentile(bounds, [0, 0, 5], 99) == 2.0
    with pytest.raises(ValueError, match="percentile"):
        bucket_percentile(bounds, [1, 0, 0], 150)
    with pytest.raises(ValueError, match="counts"):
        bucket_percentile(bounds, [1, 0], 50)  # missing +Inf entry


def test_histogram_percentile_and_series():
    r = Registry()
    h = r.histogram("p_seconds", "", buckets=(0.1, 1.0))
    assert math.isnan(h.percentile(50))  # empty reads NaN, not 0
    for v in (0.05, 0.5, 0.6, 99.0):
        h.observe(v)
    assert 0 < h.percentile(50) <= 1.0
    cell = h.series()[()]
    assert cell["count"] == 4 and cell["sum"] == pytest.approx(100.15)
    assert cell["bounds"] == [0.1, 1.0] and sum(cell["counts"]) == 4


# ---------------------------------------------------------------------------
# Profile artifact: round-trip + topology gate
# ---------------------------------------------------------------------------

def _tiny_profile(mesh=None, **measured):
    from fluxdistributed_tpu.compilation import topology_fingerprint
    from fluxdistributed_tpu.obs.profile import describe_topology

    return Profile(fingerprint=topology_fingerprint(mesh=mesh),
                   topology=describe_topology(mesh),
                   static={"model": None, "step": None, "variants": {}},
                   measured=dict(measured), meta={"producer": "test"})


def test_profile_save_load_round_trip(tmp_path):
    mesh = mesh_lib.data_mesh(8)
    prof = _tiny_profile(mesh, phases={"dispatch": {"sum": 1.0,
                                                    "count": 4}})
    path = str(tmp_path / "p.json")
    prof.save(path)
    # on-disk: strict JSON with the documented schema tag
    doc = json.loads(open(path).read())
    assert doc["schema"] == "fdtpu-profile/v2"
    assert doc["created_unix"] > 0
    back = Profile.load(path)
    assert back.fingerprint == prof.fingerprint
    assert back.measured == prof.measured
    assert back.topology["device_count"] == 8
    # same-topology verify passes and chains
    assert back.verify(mesh) is back


def test_profile_load_rejects_wrong_schema(tmp_path):
    path = tmp_path / "bad.json"
    path.write_text(json.dumps({"schema": "something/v9"}))
    with pytest.raises(ValueError, match="fdtpu-profile/v1"):
        Profile.load(str(path))


def test_profile_verify_rejects_topology_mismatch(tmp_path):
    mesh = mesh_lib.data_mesh(8)
    prof = _tiny_profile(mesh)
    path = str(tmp_path / "p.json")
    prof.save(path)
    # a tampered/foreign fingerprint must be rejected with BOTH
    # topologies named in the error
    doc = json.loads(open(path).read())
    doc["fingerprint"] = "0" * 16
    open(path, "w").write(json.dumps(doc))
    with pytest.raises(ProfileMismatch, match="do not transfer"):
        Profile.load(path).verify(mesh)
    # and a mesh-shape change alone flips the fingerprint too
    with pytest.raises(ProfileMismatch):
        _tiny_profile(mesh).verify(mesh_lib.data_mesh(4))


# ---------------------------------------------------------------------------
# static costs: staged-out model + real prepared step (lower-only)
# ---------------------------------------------------------------------------

def test_lm_layer_costs_depth_difference():
    from fluxdistributed_tpu.models import lm_tiny

    model = lm_tiny(vocab=64, depth=4, dim=32, num_heads=2, mlp_dim=64)
    costs = lm_layer_costs(model, batch_size=2, seqlen=16)
    assert costs["depth"] == 4
    for part in ("block", "outer", "total"):
        assert costs[part]["flops"] > 0
        assert costs[part]["bytes"] > 0
    # affine-in-depth consistency: total = outer + depth * block
    assert costs["total"]["flops"] == pytest.approx(
        costs["outer"]["flops"] + 4 * costs["block"]["flops"])
    # one decoder block dominates the tiny outer at seqlen 16? not
    # necessarily (vocab head) — but both must be finite and the block
    # cost must scale with nothing hidden: pricing again is identical
    assert lm_layer_costs(model, 2, 16)["block"] == costs["block"]


def test_step_cost_prices_prepared_step_and_collect_profile():
    from fluxdistributed_tpu import optim
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.models import SimpleCNN
    from fluxdistributed_tpu.train import prepare_training
    from fluxdistributed_tpu.train.trainer import _dummy_batch

    mesh = mesh_lib.data_mesh(8)
    ds = SyntheticDataset(nsamples=32, nclasses=4, shape=(8, 8, 3))
    task = prepare_training(SimpleCNN(num_classes=4), ds,
                            optim.adam(1e-3), mesh=mesh, batch_size=16,
                            cycles=1)
    batch = _dummy_batch(ds, None, 16, mesh, 1, seed=0)
    cost = step_cost(task.step_fn, (task.state, batch))
    assert cost is not None and cost["flops"] > 0

    reg = Registry()
    reg.histogram("fdtpu_train_phase_seconds", "", labelnames=("phase",)
                  ).labels(phase="dispatch").observe(0.25)
    reg.counter("fdtpu_train_steps_total", "").inc(3)
    prof = collect_profile(task, registry=reg, batch=batch)
    assert prof.static["step"]["flops"] == cost["flops"]
    assert prof.measured["phases"]["dispatch"]["count"] == 1
    assert prof.measured["counters"]["fdtpu_train_steps_total"] == 3
    assert prof.meta["model"] == "SimpleCNN"
    prof.verify(mesh)  # recorded on THIS topology


def test_step_cost_degrades_to_none_on_unlowerable():
    assert step_cost(lambda a: a, (1,)) is None  # no .lower


def test_measured_from_registry_skips_empty():
    reg = Registry()
    reg.histogram("fdtpu_train_phase_seconds", "", labelnames=("phase",))
    out = measured_from_registry(reg)
    assert "phases" not in out or out["phases"] == {}


# ---------------------------------------------------------------------------
# modeled vs measured bubble accounting
# ---------------------------------------------------------------------------

def test_modeled_bubble_reduces_to_classic_formula():
    for S, M in ((4, 4), (4, 8), (8, 16)):
        assert modeled_bubble([1.0] * S, M) == pytest.approx(
            (S - 1) / (M + S - 1))
    # an imbalanced stage worsens the bubble beyond the uniform formula
    assert modeled_bubble([1.0, 1.0, 1.0, 2.0], 8) > (4 - 1) / (8 + 4 - 1)
    # degenerate inputs take the documented 0.0 fallback, never raise
    assert modeled_bubble([], 8) == 0.0
    assert modeled_bubble([0.0, 0.0], 8) == 0.0


def test_stage_costs_split_blocks_and_outer():
    model_costs = {"depth": 8, "block": {"flops": 10.0},
                   "outer": {"flops": 4.0}}
    stages = stage_costs_from_static(model_costs, 4)
    assert len(stages) == 4
    assert sum(stages) == pytest.approx(8 * 10.0 + 4.0)
    assert stages[0] == pytest.approx(2 * 10.0 + 2.0)  # outer/2 first
    assert stages[-1] == pytest.approx(2 * 10.0 + 2.0)  # outer/2 last
    # remainder blocks land on the leading stages
    stages = stage_costs_from_static(model_costs, 3)
    assert [round(s - (2.0 if i in (0, 2) else 0), 1)
            for i, s in enumerate(stages)] == [30.0, 30.0, 20.0]


def test_bubble_report_recovers_planted_bubble():
    """Rows manufactured from the schedule model itself must round-trip:
    t(M) = (M + S - 1) * tau  =>  measured == modeled == classic."""
    S, tau = 4, 2.0
    rows = [{"M": M, "S": S, "step_ms": (M + S - 1) * tau}
            for M in (4, 8, 16, 32)]
    prof = Profile(fingerprint="x", measured={"pp_rows": rows},
                   static={"model": None})
    rep = bubble_report(prof)
    for r in rep:
        classic = (S - 1) / (r["M"] + S - 1)
        assert r["measured_bubble"] == pytest.approx(classic, abs=1e-3)
        assert r["modeled_bubble"] == pytest.approx(classic, abs=1e-3)
        assert r["fit_ms_per_microbatch"] == pytest.approx(tau)
        assert r["fit_fixed_ms"] == pytest.approx((S - 1) * tau)


def test_bubble_report_needs_two_rows():
    prof = Profile(fingerprint="x",
                   measured={"pp_rows": [{"M": 4, "S": 4, "step_ms": 1}]})
    with pytest.raises(ValueError, match=">= 2"):
        bubble_report(prof)
