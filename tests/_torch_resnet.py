"""Minimal torch ResNet with torchvision-compatible parameter names.

Test fixture only: torchvision is not in this image, so this builds the
standard ResNet architecture (He et al. 2015, v1.5 stride placement) with
exactly the state_dict layout torchvision exports (`conv1`, `bn1`,
`layer{1-4}.{i}.conv{j}/bn{j}/downsample.{0,1}`, `fc`) — the layout
``models/torch_import.py`` consumes.  Used to validate the importer and
the flax model numerically without network access.
"""

from __future__ import annotations

import torch
import torch.nn as nn


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, cin, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, planes, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.relu = nn.ReLU(inplace=True)
        self.conv2 = nn.Conv2d(planes, planes, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.downsample = downsample

    def forward(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return self.relu(y + idt)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, planes, 1, 1, 0, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * 4, 1, 1, 0, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * 4)
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample

    def forward(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        return self.relu(y + idt)


class TorchResNet(nn.Module):
    def __init__(self, block, layers, num_classes=1000, width=64):
        super().__init__()
        self.inplanes = width
        self.conv1 = nn.Conv2d(3, width, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        for i, n in enumerate(layers):
            setattr(self, f"layer{i + 1}",
                    self._make_layer(block, width * (2 ** i), n, 1 if i == 0 else 2))
        self.avgpool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(self.inplanes, num_classes)

    def _make_layer(self, block, planes, nblocks, stride):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2d(self.inplanes, planes * block.expansion, 1, stride, bias=False),
                nn.BatchNorm2d(planes * block.expansion),
            )
        blocks = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        blocks += [block(self.inplanes, planes) for _ in range(nblocks - 1)]
        return nn.Sequential(*blocks)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        for i in range(4):
            x = getattr(self, f"layer{i + 1}")(x)
        x = torch.flatten(self.avgpool(x), 1)
        return self.fc(x)


def torch_resnet(depth: int, num_classes: int = 1000) -> TorchResNet:
    cfg = {18: (BasicBlock, [2, 2, 2, 2]), 34: (BasicBlock, [3, 4, 6, 3]),
           50: (Bottleneck, [3, 4, 6, 3]), 101: (Bottleneck, [3, 4, 23, 3]),
           152: (Bottleneck, [3, 8, 36, 3])}
    block, layers = cfg[depth]
    return TorchResNet(block, layers, num_classes=num_classes)
