"""FDT305 positive: the thread target mutates a module global with no
lock held — concurrent with every other worker and the main thread."""
import threading

_STATS = {}


def _worker():
    _STATS["ticks"] = _STATS.get("ticks", 0) + 1  # unlocked


def start():
    threading.Thread(target=_worker, daemon=True).start()
