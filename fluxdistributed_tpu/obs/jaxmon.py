"""``jax.monitoring`` listeners: live compile counters + steady-state
recompile flagging.

XLA recompiles are the silent throughput killer of a JAX service: one
stray shape change turns a 2 ms decode step into a 30 s stall, and
nothing in the program output says so.  JAX already emits monitoring
events for every backend compile (``/jax/core/compile/
backend_compile_duration`` — the same hooks TensorBoard's profiler
consumes); this module folds them into the metrics registry:

* ``fdtpu_jax_compiles_total`` / ``fdtpu_jax_compile_seconds_total`` —
  every backend compile, count and wall seconds;
* ``fdtpu_jax_trace_seconds_total`` — jaxpr tracing time (host-side
  program construction, distinct from XLA compile time);
* ``fdtpu_jax_steady_recompiles_total`` — compiles that happened AFTER
  the caller declared steady state.  The serve engine's "ONE decode
  compile" invariant (tests assert it offline) becomes a live metric:
  scrape nonzero here in production and something is recompiling.
* ``fdtpu_jax_cache_hits_total`` / ``fdtpu_jax_cache_misses_total`` /
  ``fdtpu_jax_cache_saved_seconds_total`` — the persistent compilation
  cache's own event stream (``/jax/compilation_cache/*``).  NOTE: a
  persistent-cache HIT still records a ``backend_compile_duration``
  event on this jax (the timer brackets compile-or-load), so "zero new
  compiles" is asserted as ``cache_misses == 0``, not as a zero compile
  counter.

Install is idempotent and process-global (JAX offers registration but
no deregistration); the listener holds only module state and costs one
dict lookup per COMPILE, i.e. nothing at steady state.
"""

from __future__ import annotations

import contextlib
import sys
import threading
from typing import Callable, Optional

from .metrics import Registry, get_registry

__all__ = [
    "install",
    "installed",
    "mark_steady",
    "clear_steady",
    "steady_state",
    "compile_count",
    "compile_seconds",
    "cache_hits",
    "cache_misses",
    "compile_seconds_saved",
    "steady_recompiles",
]

BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"
CACHE_SAVED_EVENT = "/jax/compilation_cache/compile_time_saved_sec"

_lock = threading.Lock()
_installed = False
_steady = False
_registry: Optional[Registry] = None
_warn: Callable[[str], None] = lambda msg: print(msg, file=sys.stderr)


def _listener(event: str, duration: float, **kwargs) -> None:
    reg = _registry
    if reg is None:  # pragma: no cover — install() always binds one
        return
    if event == BACKEND_COMPILE_EVENT:
        reg.counter(
            "fdtpu_jax_compiles_total", "XLA backend compiles"
        ).inc()
        reg.counter(
            "fdtpu_jax_compile_seconds_total", "XLA backend compile seconds"
        ).inc(duration)
        if _steady:
            reg.counter(
                "fdtpu_jax_steady_recompiles_total",
                "compiles observed AFTER steady state was declared "
                "(any nonzero value means something is recompiling)",
            ).inc()
            _warn(
                f"obs.jaxmon: steady-state RECOMPILE ({duration:.2f}s) — "
                "an input shape/dtype or static argument changed after "
                "warmup; check bucket sizes and batch shapes"
            )
    elif event == TRACE_EVENT:
        reg.counter(
            "fdtpu_jax_trace_seconds_total", "jaxpr trace seconds"
        ).inc(duration)
    elif event == CACHE_SAVED_EVENT:
        reg.counter(
            "fdtpu_jax_cache_saved_seconds_total",
            "compile wall seconds skipped by persistent-cache hits",
        ).inc(max(duration, 0.0))


def _event_listener(event: str, **kwargs) -> None:
    """Plain (non-duration) monitoring events: the persistent
    compilation cache's hit/miss stream."""
    reg = _registry
    if reg is None:  # pragma: no cover — install() always binds one
        return
    if event == CACHE_HIT_EVENT:
        reg.counter(
            "fdtpu_jax_cache_hits_total",
            "XLA compiles served from the persistent compilation cache",
        ).inc()
    elif event == CACHE_MISS_EVENT:
        reg.counter(
            "fdtpu_jax_cache_misses_total",
            "XLA compiles the persistent compilation cache could not serve",
        ).inc()


def install(registry: Optional[Registry] = None,
            warn: Optional[Callable[[str], None]] = None) -> None:
    """Register the monitoring listener (idempotent; first registry
    passed wins — JAX has no listener deregistration, so the binding is
    process-lifetime)."""
    global _installed, _registry, _warn
    import jax.monitoring

    with _lock:
        if registry is not None and _registry is None:
            _registry = registry
        if _registry is None:
            _registry = get_registry()
        if warn is not None:
            _warn = warn
        if _installed:
            return
        # pre-register so /metrics shows explicit zeros before the
        # first compile (absence would read as "not instrumented")
        _registry.counter("fdtpu_jax_compiles_total", "XLA backend compiles")
        _registry.counter(
            "fdtpu_jax_compile_seconds_total", "XLA backend compile seconds"
        )
        _registry.counter(
            "fdtpu_jax_steady_recompiles_total",
            "compiles observed AFTER steady state was declared "
            "(any nonzero value means something is recompiling)",
        )
        _registry.counter(
            "fdtpu_jax_cache_hits_total",
            "XLA compiles served from the persistent compilation cache",
        )
        _registry.counter(
            "fdtpu_jax_cache_misses_total",
            "XLA compiles the persistent compilation cache could not serve",
        )
        _registry.counter(
            "fdtpu_jax_cache_saved_seconds_total",
            "compile wall seconds skipped by persistent-cache hits",
        )
        jax.monitoring.register_event_duration_secs_listener(_listener)
        jax.monitoring.register_event_listener(_event_listener)
        _installed = True


def installed() -> bool:
    return _installed


def mark_steady() -> None:
    """Declare warmup over: every compile from here on is a flagged
    (counted + warned) steady-state recompile."""
    global _steady
    install()
    _steady = True


def clear_steady() -> None:
    global _steady
    _steady = False


@contextlib.contextmanager
def steady_state():
    """``with jaxmon.steady_state():`` — flag recompiles inside the
    block (restores the previous flag on exit, so nesting composes)."""
    global _steady
    install()
    prev = _steady
    _steady = True
    try:
        yield
    finally:
        _steady = prev


def compile_count() -> float:
    reg = _registry or get_registry()
    return reg.value("fdtpu_jax_compiles_total")


def compile_seconds() -> float:
    reg = _registry or get_registry()
    return reg.value("fdtpu_jax_compile_seconds_total")


def cache_hits() -> float:
    reg = _registry or get_registry()
    return reg.value("fdtpu_jax_cache_hits_total")


def cache_misses() -> float:
    reg = _registry or get_registry()
    return reg.value("fdtpu_jax_cache_misses_total")


def compile_seconds_saved() -> float:
    reg = _registry or get_registry()
    return reg.value("fdtpu_jax_cache_saved_seconds_total")


def steady_recompiles() -> float:
    reg = _registry or get_registry()
    return reg.value("fdtpu_jax_steady_recompiles_total")
