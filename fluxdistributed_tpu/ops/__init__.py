from .losses import logitcrossentropy, crossentropy, mse
from .metrics import topkaccuracy, onehot
from .attention import dot_product_attention, blockwise_attention

__all__ = [
    "logitcrossentropy",
    "crossentropy",
    "mse",
    "topkaccuracy",
    "onehot",
    "dot_product_attention",
    "blockwise_attention",
]
