"""FDT304 negative: the worker is daemonized AND joined on the stop
path; every callback gauge is unregistered in close()."""
import threading


class Pump:
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self):
        self._thread.join(timeout=1.0)

    def _run(self):
        pass


class Gauges:
    def __init__(self, registry):
        self.registry = registry
        self._callback_gauges = ["fdtpu_toy_depth"]
        registry.gauge("fdtpu_toy_depth", "toy").set_function(
            lambda: 0.0)

    def close(self):
        for name in self._callback_gauges:
            self.registry.unregister(name)
