"""Space-to-depth stem: exact equivalence with the 7x7/2 stem.

The MLPerf-style TPU stem optimization (models/resnet.py:space_to_depth)
must be a pure re-layout — same arithmetic, MXU-shaped.  These tests
prove it: transforming the 7x7 kernel with s2d_stem_kernel and feeding
space_to_depth(x) reproduces the standard model's output to float32
tolerance, end to end through the full ResNet.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# tier-2 (slow): two full-ResNet compiles per test (s2d vs 7x7 stem) — the tier-1 iteration loop must fit the
# 870s verify window (ROADMAP); CI's slow job still runs this file
pytestmark = pytest.mark.slow

from fluxdistributed_tpu.models import resnet18, resnet50
from fluxdistributed_tpu.models.resnet import s2d_stem_kernel, space_to_depth


def test_space_to_depth_layout():
    x = np.arange(2 * 8 * 8 * 3, dtype=np.float32).reshape(2, 8, 8, 3)
    y = space_to_depth(x)
    assert y.shape == (2, 4, 4, 12)
    # channel group (r_h*2 + r_w)*C + c holds pixel (2q_h+r_h, 2q_w+r_w)
    for rh in range(2):
        for rw in range(2):
            for c in range(3):
                np.testing.assert_array_equal(
                    y[:, 1, 2, (rh * 2 + rw) * 3 + c],
                    x[:, 2 + rh, 4 + rw, c],
                )


@pytest.mark.parametrize("ctor", [resnet18, resnet50])
def test_s2d_model_matches_standard(ctor):
    """Full-model equivalence: same params except the re-laid-out stem
    kernel, identical logits (f32 compute isolates layout from rounding)."""
    model = ctor(num_classes=10, dtype=jnp.float32)
    s2d = ctor(num_classes=10, dtype=jnp.float32, space_to_depth=True)

    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (2, 64, 64, 3)).astype(np.float32)
    v = model.init(jax.random.PRNGKey(0), x[:1], train=True)

    params = jax.device_get(v["params"])
    w7 = params["stem_conv"]["kernel"]
    params_s2d = dict(params)
    params_s2d["stem_conv"] = {"kernel": jnp.asarray(s2d_stem_kernel(w7))}
    # the s2d model's own init must agree on every shape
    shapes = jax.tree.map(
        np.shape, s2d.init(jax.random.PRNGKey(1), space_to_depth(x[:1]), train=True)["params"]
    )
    assert shapes == jax.tree.map(np.shape, params_s2d)

    variables = {"params": params, "batch_stats": v["batch_stats"]}
    variables_s2d = {"params": params_s2d, "batch_stats": v["batch_stats"]}
    out = model.apply(variables, x, train=False)
    # host-side pre-transform AND the in-graph fallback must both match
    out_host = s2d.apply(variables_s2d, space_to_depth(x), train=False)
    out_graph = s2d.apply(variables_s2d, x, train=False)
    np.testing.assert_allclose(np.asarray(out_host), np.asarray(out), rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out_graph), np.asarray(out), rtol=1e-5, atol=1e-4)


def test_s2d_trains_one_step():
    """The s2d variant runs through the compiled DP train step."""
    import fluxdistributed_tpu as fd
    from fluxdistributed_tpu import optim, sharding
    from fluxdistributed_tpu.parallel import TrainState, make_train_step
    from fluxdistributed_tpu.parallel.dp import flax_loss_fn

    mesh = fd.data_mesh()
    model = resnet18(num_classes=4, dtype=jnp.float32, space_to_depth=True)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (8, 32, 32, 3)).astype(np.float32)
    y = fd.onehot(rng.integers(0, 4, 8), 4)
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    mstate = {k: v for k, v in variables.items() if k != "params"}
    opt = optim.momentum(0.1, 0.9)
    step = make_train_step(flax_loss_fn(model, fd.logitcrossentropy), opt, mesh)
    state = TrainState.create(
        sharding.replicate(variables["params"], mesh), opt,
        model_state=sharding.replicate(mstate, mesh),
    )
    b = sharding.shard_batch({"image": np.asarray(space_to_depth(x)),
                              "label": np.asarray(y)}, mesh)
    state, m = step(state, b)
    assert np.isfinite(float(m["loss"]))


def test_s2d_through_trainer_with_transform():
    """The full user path: prepare_training(transform=space_to_depth
    re-layout) -> train with val eval -> whole-dataset evaluate, all fed
    the transformed layout consistently."""
    import fluxdistributed_tpu as fd
    from fluxdistributed_tpu import optim
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.train import evaluate, prepare_training, train
    from fluxdistributed_tpu.train.logging import NullLogger

    mesh = fd.data_mesh(8)
    ds = SyntheticDataset(nsamples=64, nclasses=4, shape=(16, 16, 3))
    model = resnet18(num_classes=4, dtype=jnp.float32, space_to_depth=True)

    def t(imgs, labels):
        return np.ascontiguousarray(space_to_depth(imgs)), labels

    task = prepare_training(
        model, ds, optim.momentum(0.05, 0.9), mesh=mesh, batch_size=16,
        cycles=6, topk=(1,), transform=t, val_dataset=ds, val_samples=16,
    )
    train(task, print_every=0, eval_every=3, topk=(1,), logger=NullLogger())
    assert int(task.state.step) == 6
    out = evaluate(task, ds, batch_size=32, topk=(1,))
    assert out["samples"] == 64 and np.isfinite(out["loss"])
