"""Slot-based continuous-batching engine for ``TransformerLM``.

The ROADMAP's inference half ("serve heavy traffic") needs many
concurrent requests per chip, but per-request Python loops throw away
exactly what makes TPUs fast: a small set of fixed-shape compiled XLA
programs (arXiv:1810.09868's core lesson).  This engine serves ANY
number of requests through a handful of fixed-shape programs:

* **Bucketed prefill** (dense layout) — a batch-1 scalar-index decode
  forward over the prompt padded up to a shape bucket ({128, 512, 2048}
  by default), so the jit cache holds one compiled prefill per bucket
  and stays warm no matter what prompt lengths arrive.  Right-padding
  is safe by construction: a position's cache slot is a function of the
  position alone, the causal mask admits only positions ≤ the query's,
  and every pad entry is overwritten by the real token for its position
  before it could ever become attendable.
* **Fixed-slot decode** — ONE single-token step over all ``max_slots``
  cache rows of a ``slot_decode=True`` model (per-slot cursors, see
  models/transformer_lm.py), compiled once.  Finished requests free
  their slot; admissions splice a prefilled batch-1 cache into a free
  row mid-flight without touching the compiled step.

Two **cache layouts** (``serve/cache_layout.py``) sit under those
programs:

* ``layout="dense"`` (default) — the original fixed-slot cache:
  ``max_slots × (sinks + window | max_len)`` rows per layer,
  ring-buffer + pinned sinks when windowed (sized EXACTLY: the dynamic
  valid-length prefill operand gates pad writes out of the ring, so no
  slack rows are reserved).  HBM scales with capacity.
* ``layout="paged"`` — a shared pool of ``kv_blocks`` fixed-size KV
  blocks per layer with per-slot page tables carried as device-side
  int32 *data*, so HBM scales with live tokens and freed blocks return
  to the pool on EOS.  Prefill runs in fixed-size **chunks** written
  straight through the page table (no splice program), which lets the
  scheduler interleave a long prompt's chunks with decode ticks; with
  ``prefix_cache=True`` completed prompt blocks are hash-keyed and
  refcounted so shared prefixes prefill once.  Page-table updates are
  data fed to the same compiled programs — the ONE-decode-compile
  invariant holds across admissions, frees, growth and prefix reuse.

Greedy decoding is token-for-token identical to sequential
:func:`models.generate` under BOTH layouts (the golden parity tests,
tests/test_serve_engine.py and tests/test_serve_paged.py); temperature
sampling uses an independent per-request key stream (``fold``-free:
keys split inside the compiled step), so it is distribution-identical
but not key-stream-identical.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer_lm import KV_QUANTS, TransformerLM, make_decode_cache
from .cache_layout import DenseLayout, PagedLayout

__all__ = ["LMEngine", "DEFAULT_BUCKETS", "DEFAULT_KV_BLOCK_SIZE"]

DEFAULT_BUCKETS = (128, 512, 2048)
DEFAULT_KV_BLOCK_SIZE = 16

#: cache leaves that carry one row per slot (everything else is a
#: shared block pool in the paged layout)
_PER_ROW_LEAVES = ("cache_index", "pos_index", "page_table", "slot_pos",
                   "slot_live", "valid_len")


def _jit_cache_size(fn) -> int:
    """Compile count of a jitted callable (-1 if this jax can't say).
    The decode bench asserts steady state holds at ONE decode compile."""
    probe = getattr(fn, "_cache_size", None)
    try:
        return int(probe()) if callable(probe) else -1
    except Exception:
        return -1


def _leaf_name(path) -> Optional[str]:
    return getattr(path[-1], "key", None)


class _PrefillState:
    """In-flight prefill for one slot — the scheduler advances it one
    chunk per call so a long prompt interleaves with decode ticks."""

    __slots__ = ("slot", "tokens", "temperature", "key", "plen", "pos",
                 "small", "padded", "rid")

    def __init__(self, slot, tokens, temperature, key, pos=0, small=None,
                 rid=None):
        self.slot = slot
        self.tokens = [int(t) for t in tokens]
        self.temperature = float(temperature)
        self.key = key
        self.plen = len(self.tokens)
        self.pos = pos        # next prompt position to process
        self.small = small    # dense layout: carried batch-1 cache
        self.padded = 0       # padded tokens computed so far
        self.rid = rid        # request trace id (obs.reqtrace) — pure
        #                       host metadata; never enters a program


class LMEngine:
    """Compiled-program pool + slot cache for continuous batching.

    ``model`` is the TRAINING-mode ``TransformerLM`` (the engine derives
    its own ``decode=True`` clones); ``params`` its trained parameters.
    The engine is not thread-safe by itself — the scheduler serializes
    all calls onto one loop thread.

    Cold start (:mod:`fluxdistributed_tpu.compilation`): ``prewarm=True``
    runs :meth:`warmup` at construction — every program compiles before
    the first request instead of inside its latency.  ``aot_dir`` goes
    further: each program is loaded from a serialized on-disk executable
    when one matches this topology + model, else compiled now and
    serialized for the next process (a restarted server skips its whole
    compile pool).

    Layout knobs:

    * ``layout`` — ``"dense"`` (default, the original fixed-slot cache)
      or ``"paged"`` (shared KV block pool + per-slot page tables).
    * ``kv_block_size`` / ``kv_blocks`` — paged pool geometry: rows per
      block and blocks per layer.  ``kv_blocks=None`` sizes the pool for
      full capacity (``max_slots`` worst-case slots — no memory saving,
      but never refuses what dense would serve); size it SMALLER to make
      HBM scale with live tokens and let admission backpressure handle
      the tail.
    * ``prefill_chunk`` — prompt positions per prefill chunk.  Paged
      prefill is always chunked (default 128); a dense engine stays on
      whole-bucket prefill unless a chunk size is given.
    * ``prefix_cache`` — paged only, plain attention only: completed
      prompt blocks are prefix-hash-keyed and refcounted, so repeated
      system prompts prefill once (copy-on-write at the divergence
      block — shared blocks are never written).
    """

    def __init__(
        self,
        model: TransformerLM,
        params,
        *,
        max_slots: int = 8,
        max_len: int = 1024,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        prewarm: bool = False,
        aot_dir: str | None = None,
        layout: str = "dense",
        kv_block_size: int = DEFAULT_KV_BLOCK_SIZE,
        kv_blocks: int | None = None,
        prefill_chunk: int | None = None,
        prefix_cache: bool = False,
        attention_impl: str = "xla",
        kv_dtype: str | None = None,
    ):
        if model.moe_every:
            raise ValueError(
                "the serving engine supports dense models only (MoE decode "
                "routes per-token expert dispatch; build the model with "
                "moe_every=0)")
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        if layout not in ("dense", "paged"):
            raise ValueError(f"unknown cache layout {layout!r} (dense|paged)")
        if not model.use_rope:
            if model.max_len is None or model.max_len < max_len:
                raise ValueError(
                    f"use_rope=False needs the model's learned positional "
                    f"table to cover the engine's max_len ({max_len}); got "
                    f"model.max_len={model.max_len}")
        if prefix_cache and layout != "paged":
            raise ValueError(
                "prefix_cache=True needs layout='paged' (the dense layout "
                "has no shareable blocks)")
        if prefix_cache and model.window is not None:
            raise ValueError(
                "prefix_cache is not supported with sliding-window "
                "attention: ring eviction makes a stored block's contents "
                "depend on everything decoded after it, so equal prefixes "
                "stop implying equal blocks. Drop window= or prefix_cache.")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        if attention_impl not in ("xla", "pallas"):
            raise ValueError(
                f"unknown attention_impl {attention_impl!r} (xla|pallas)")
        kv_quant = kv_dtype or "none"
        if kv_quant not in KV_QUANTS:
            raise ValueError(
                f"unknown kv_dtype {kv_dtype!r} "
                f"(None|{'|'.join(q for q in KV_QUANTS if q != 'none')})")
        self.attention_impl = attention_impl
        self.kv_quant = kv_quant
        self.layout_name = layout
        self.max_slots = max_slots
        self.max_len = max_len
        if layout == "paged":
            self.prefill_chunk: Optional[int] = min(
                prefill_chunk or 128, max_len)
            self.buckets: Tuple[int, ...] = ()
        else:
            self.prefill_chunk = (
                min(prefill_chunk, max_len) if prefill_chunk else None)
            # clamp buckets to the cache and always top out AT max_len:
            # without the top bucket, a prompt in (largest bucket,
            # max_len] would be rejected even though the slot cache can
            # hold it
            bl = sorted({int(b) for b in buckets if 0 < int(b) < max_len}
                        | {max_len})
            self.buckets = tuple(bl)
        #: chunked prefill (paged always; dense with prefill_chunk=)
        #: advances through prefill_begin/prefill_step — the scheduler
        #: interleaves chunks with decode ticks
        self.prefill_incremental = self.prefill_chunk is not None
        # store weights in the model's COMPUTE dtype once, up front.
        # flax casts f32-stored params to `dtype` inside every apply;
        # generate()'s scan hoists that cast out of its loop, but the
        # engine's per-token step would pay the full-tree cast EVERY
        # step (it dominated the step on CPU).  Pre-casting is the same
        # rounding, applied once — numerics identical, and the resident
        # weight footprint halves for bf16 models.
        self.params = jax.tree.map(
            lambda x: jnp.asarray(
                x, model.dtype if jnp.issubdtype(
                    jnp.asarray(x).dtype, jnp.floating) else None),
            params)
        self.model = model
        # decode=True rejects attn_fn by design (the cache path always
        # uses the dense core — the math is identical for gathered
        # weights); dropout is inference-irrelevant.  Padded prefill is
        # made safe by the DYNAMIC VALID-LENGTH operand: every prefill/
        # chunk program receives the call's real token count as cache
        # data (``valid_len`` — see models.transformer_lm.VALID_UNGATED)
        # and the model gates pad positions out of the windowed ring
        # write, so a pad can never write OR evict an in-band key.  The
        # ring is therefore sized exactly sinks + window — the old
        # ``ring_slack`` over-allocation (largest pad run: inter-bucket
        # gap / prefill chunk) is gone, and the reclaimed rows show up
        # directly in ``reserved_kv_bytes``.
        #: per-slot per-layer KV rows logically addressable:
        #: sinks + window for windowed models (exact), max_len otherwise
        self.kv_rows_per_slot = (
            max_len if model.window is None
            else min(model.window + model.sinks, max_len))
        if layout == "paged":
            pages_per_slot = -(-self.kv_rows_per_slot // kv_block_size)
            if kv_blocks is None:
                kv_blocks = max_slots * pages_per_slot
            self.layout = PagedLayout(
                max_slots, self.kv_rows_per_slot, kv_block_size,
                kv_blocks, prefix_cache=prefix_cache, kv_quant=kv_quant)
            paged_kw = dict(kv_block_size=kv_block_size, kv_blocks=kv_blocks)
        else:
            self.layout = DenseLayout(max_slots, self.kv_rows_per_slot,
                                      kv_quant=kv_quant)
            paged_kw = dict()
        # ring_slack pinned to 0 on the clones: the engine's layout
        # math (kv_rows_per_slot, pages_per_slot, reserved_kv_bytes)
        # sizes the ring at exactly sinks + window — a user model's
        # retention slack must not silently desynchronize the cache
        # allocation from that accounting
        self.decode_model = model.clone(
            decode=True, slot_decode=True, attn_fn=None, dropout=0.0,
            ring_slack=0, attention_impl=attention_impl,
            kv_quant=kv_quant, **paged_kw)
        self.cache = make_decode_cache(self.decode_model, max_slots, max_len)
        if layout == "dense":
            # the prefill program runs whole buckets/chunks (t > 1), so
            # its attention stays XLA whatever the decode impl — but it
            # must share the decode model's QUANT setting: the cache it
            # fills is the cache the splice hands to the decode step
            self.prefill_model = model.clone(
                decode=True, slot_decode=False, attn_fn=None, dropout=0.0,
                ring_slack=0, attention_impl=attention_impl,
                kv_quant=kv_quant)
            # reusable zero template: _prefill never mutates its input,
            # so one template serves every admission
            self._prefill_zero = make_decode_cache(
                self.prefill_model, 1, max_len)
        else:
            # paged prefill is the decode model itself at chunk shape —
            # chunks write straight through the page table, no splice
            self.prefill_model = None
            self._prefill_zero = None
        # per-slot sampling state lives ON DEVICE between steps — the
        # decode loop's only host traffic is the one token sync the
        # scheduler needs for stop checks and streaming
        self._tok = jnp.zeros((max_slots,), jnp.int32)
        self._temp = jnp.zeros((max_slots,), jnp.float32)
        self._keys = jnp.zeros((max_slots, 2), jnp.uint32)
        # paged host mirrors: which slots are decoding, and each slot's
        # next write position (drives just-in-time block allocation)
        self._decoding: set = set()
        self._host_pos = [0] * max_slots
        self._prefill_jit = jax.jit(self._prefill_impl)
        # donate the carried state (slot cache, tokens, keys): every
        # step/splice REPLACES them, so XLA may update the KV in place
        # instead of copying the whole slot cache each call — at serving
        # scale that copy is the step's largest memory traffic after the
        # weights themselves
        self._insert_jit = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._step_jit = jax.jit(self._step_impl, donate_argnums=(1, 2, 4))
        self._sample1_jit = jax.jit(self._sample)
        self._chunk_jit = jax.jit(self._chunk_impl, donate_argnums=(1,))
        self._bind_jit = jax.jit(self._bind_impl, donate_argnums=(0,))
        self._release_jit = jax.jit(self._release_impl, donate_argnums=(0,))
        # AOT executables keyed by program name (prefill additionally by
        # bucket — one fixed shape each); populated by _load_aot, empty
        # when aot_dir is None so every call falls through to the jits
        self._aot: dict = {}
        if aot_dir:
            self._load_aot(aot_dir)
        if prewarm:
            self.warmup()

    # ---- compiled programs ------------------------------------------------

    def _prefill_impl(self, params, cache0, toks, plen):
        """Whole padded prompt (or one chunk of it) in one parallel
        pass; returns the filled batch-1 cache and the logits at the
        LAST REAL position (the distribution of the next token).

        ``plen`` — the call's REAL token count — is also the dynamic
        valid-length operand: it arms the windowed ``valid_len`` write
        gate (cache DATA, so every prompt length shares ONE compiled
        program per bucket) so pad positions never write into, or
        evict from, the exactly-sized ring."""
        if self.model.window is not None:
            def arm(path, leaf):
                if _leaf_name(path) == "valid_len":
                    return jnp.full_like(leaf, plen)
                return leaf

            cache0 = jax.tree_util.tree_map_with_path(arm, cache0)
        logits, mut = self.prefill_model.apply(
            {"params": params, "cache": cache0}, toks, train=False,
            mutable=["cache"],
        )
        last = jax.lax.dynamic_slice_in_dim(logits, plen - 1, 1, axis=1)[:, 0]
        return mut["cache"], last.astype(jnp.float32)

    def _insert_impl(self, big, small, slot, plen):
        """Splice a prefilled batch-1 cache into slot row ``slot``.

        Cursor leaves are set to the TRUE prompt length (the prefill ran
        over the padded bucket, so its own cursor reads bucket, not
        plen); pad K/V entries ride along and are masked/overwritten by
        construction (module docstring).
        """

        def leaf(path, bg, sm):
            name = _leaf_name(path)
            if name in ("cache_index", "pos_index"):
                return bg.at[slot].set(jnp.asarray(plen, bg.dtype))
            if name == "slot_pos":
                # scrub PAD ring entries (position >= plen) back to -1
                # ("unwritten, never attendable"): the spliced ring then
                # holds exactly what a batch-1 unpadded prefill of plen
                # tokens would hold — the parity invariant
                return bg.at[slot].set(jnp.where(sm < plen, sm, -1))
            if name == "valid_len":
                # decode rows run UNGATED (every decode write is real);
                # the gate is a per-prefill-call operand, not slot state
                return bg
            if name in ("cached_k", "cached_v",
                        "cached_k_scale", "cached_v_scale"):
                return bg.at[slot].set(sm[0])
            raise ValueError(f"unknown cache leaf {name!r}")

        return jax.tree_util.tree_map_with_path(leaf, big, small)

    def _chunk_impl(self, params, cache, toks, slot, start, nvalid, arm):
        """One paged prefill chunk straight into slot ``slot``'s pages.

        A batch-1 view of the slot's rows (shared pools pass through
        untouched) runs the decode model at chunk shape; the writeback
        then pins the cursors to ``start + nvalid`` (host truth — the
        all-slot decode step may have drifted a mid-prefill slot's
        cursor, and a padded final chunk overshoots) and scrubs pad
        ``slot_pos`` entries, exactly the dense splice's invariant.
        The view forces the ``slot_live`` write gate open (the big
        cache keeps it 0 mid-prefill so decode-tick drift writes DROP);
        ``arm=1`` on the final chunk flips the big gate live for
        decode.  Page tables are read-only here: allocation is host
        bookkeeping applied through :meth:`_bind_impl`, all of it DATA
        — this one compiled program serves every chunk of every
        prompt."""

        def take(path, leaf):
            name = _leaf_name(path)
            if name in _PER_ROW_LEAVES:
                row = jax.lax.dynamic_slice_in_dim(leaf, slot, 1, axis=0)
                if name in ("cache_index", "pos_index"):
                    row = jnp.full_like(row, start)
                if name == "slot_live":
                    row = jnp.ones_like(row)  # the chunk itself writes
                if name == "valid_len":
                    # the dynamic valid-length operand: only nvalid of
                    # this chunk's positions are real — the windowed
                    # write gate drops the pads (no ring slack needed)
                    row = jnp.full_like(row, nvalid)
                if name == "slot_pos":
                    # every ring entry >= start is cursor-drift garbage
                    # from before the slot_live gate existed for this
                    # row (e.g. a fresh admission over a just-released
                    # slot) — scrub with host truth so the windowed
                    # read-before-write can never see a position this
                    # slot has not actually written
                    row = jnp.where(row < start, row, -1)
                return row
            return leaf  # shared block pools

        view = jax.tree_util.tree_map_with_path(take, cache)
        logits, mut = self.decode_model.apply(
            {"params": params, "cache": view}, toks, train=False,
            mutable=["cache"],
        )
        new = mut["cache"]
        end = start + nvalid

        def put(path, big, small):
            name = _leaf_name(path)
            if name in ("cache_index", "pos_index"):
                return big.at[slot].set(jnp.asarray(end, big.dtype))
            if name == "slot_live":
                return big.at[slot].set(arm.astype(big.dtype))
            if name == "valid_len":
                return big  # decode rows stay ungated (VALID_UNGATED)
            if name == "slot_pos":
                return big.at[slot].set(
                    jnp.where(small[0] < end, small[0], -1))
            if name == "page_table":
                return big  # engine-owned; the model never writes it
            return small  # shared pools, mutated through the page table

        cache2 = jax.tree_util.tree_map_with_path(put, cache, new)
        last = jax.lax.dynamic_slice_in_dim(logits, nvalid - 1, 1, axis=1)[:, 0]
        return cache2, last.astype(jnp.float32)

    def _bind_impl(self, cache, slot, row):
        """Write slot ``slot``'s WHOLE page-table row in every layer
        (block ids are layer-agnostic: layer L's pool uses the same
        numbering).  The row has a fixed length (``pages_per_slot``), so
        one dispatch covers an admission's entire claimed prefix, a
        chunk's block growth, or a decode tick's boundary crossing —
        never one dispatch per page.  Page-table growth is DATA — the
        compiled decode and chunk programs never change."""

        def leaf(path, bg):
            if _leaf_name(path) == "page_table":
                return bg.at[slot].set(row.astype(bg.dtype))
            return bg

        return jax.tree_util.tree_map_with_path(leaf, cache)

    def _release_impl(self, cache, slot):
        """Park a freed paged slot: cursors to zero, page-table row and
        ring positions to -1 ("unallocated / unwritten") — writes drop,
        reads are mask-excluded, and the freed blocks are back on the
        host free list."""

        def leaf(path, bg):
            name = _leaf_name(path)
            if name in ("cache_index", "pos_index", "slot_live"):
                return bg.at[slot].set(jnp.zeros((), bg.dtype))
            if name in ("page_table", "slot_pos"):
                return bg.at[slot].set(jnp.full((), -1, bg.dtype))
            return bg

        return jax.tree_util.tree_map_with_path(leaf, cache)

    def _sample(self, logits, temp, keys):
        """Greedy/temperature next-token draw, per row.

        Same math as ``models.generate`` (f32 logits / temperature →
        categorical; argmax at temperature 0) but with an independent
        key per row, split inside the compiled program.
        """
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        pairs = jax.vmap(partial(jax.random.split, num=2))(keys)
        new_keys, subs = pairs[:, 0], pairs[:, 1]
        scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
        sampled = jax.vmap(jax.random.categorical)(subs, scaled)
        nxt = jnp.where(temp > 0, sampled.astype(jnp.int32), greedy)
        return nxt, new_keys

    def _step_impl(self, params, cache, tok, temp, keys):
        """One decode step over ALL slots: [S] tokens in, [S] out."""
        logits, mut = self.decode_model.apply(
            {"params": params, "cache": cache}, tok[:, None], train=False,
            mutable=["cache"],
        )
        nxt, new_keys = self._sample(
            logits[:, 0].astype(jnp.float32), temp, keys)
        return mut["cache"], nxt, new_keys

    # ---- cold-start: AOT executables + prewarm ----------------------------

    def _example_args(self, program: str, bucket: int | None = None):
        """Zero-filled arguments with each program's exact shapes — what
        AOT lowering and prewarm both trace/execute against."""
        if program == "prefill":
            return (self.params, self._prefill_zero,
                    jnp.zeros((1, bucket), jnp.int32),
                    jnp.asarray(1, jnp.int32))
        if program == "insert":
            return (self.cache, self._prefill_zero,
                    jnp.asarray(0, jnp.int32), jnp.asarray(1, jnp.int32))
        if program == "step":
            return (self.params, self.cache, self._tok, self._temp, self._keys)
        if program == "sample1":
            return (jnp.zeros((1, self.model.vocab), jnp.float32),
                    jnp.zeros((1,), jnp.float32),
                    jnp.zeros((1, 2), jnp.uint32))
        if program == "chunk":
            return (self.params, self.cache,
                    jnp.zeros((1, self.prefill_chunk), jnp.int32),
                    jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
                    jnp.asarray(1, jnp.int32), jnp.asarray(0, jnp.int32))
        if program == "bind":
            return (self.cache, jnp.asarray(0, jnp.int32),
                    jnp.full((self.layout.pages_per_slot,), -1, jnp.int32))
        if program == "release":
            return (self.cache, jnp.asarray(0, jnp.int32))
        raise ValueError(f"unknown engine program {program!r}")

    def _aot_jobs(self):
        """(name, jit, bucket) for every program this layout serves
        through — the AOT pool and warmup iterate the same list."""
        jobs = [("step", self._step_jit, None),
                ("sample1", self._sample1_jit, None)]
        if self.layout_name == "paged":
            jobs += [("chunk", self._chunk_jit, None),
                     ("bind", self._bind_jit, None),
                     ("release", self._release_jit, None)]
        else:
            jobs += [("insert", self._insert_jit, None)]
            shapes = set(self.buckets)
            if self.prefill_chunk:
                shapes.add(self.prefill_chunk)
            jobs += [("prefill", self._prefill_jit, b)
                     for b in sorted(shapes)]
        return jobs

    def _load_aot(self, aot_dir: str) -> None:
        """Load-or-compile every engine program as a serialized AOT
        executable under ``aot_dir``.  A process that finds matching
        files on disk skips tracing, lowering AND backend compilation
        for its entire program pool; any mismatch (topology, jaxlib,
        model shape) falls back to a fresh compile of that program,
        which is then serialized for the next process."""
        from .. import compilation

        # everything that changes a compiled program without changing
        # argument shapes (windowing, norms, rope, ...) is in the model
        # repr (config_tag scrubs the addresses a callable field like
        # attn_fn prints); max_len/buckets shape the cache and prefill,
        # and the layout knobs shape the paged pool and chunk programs
        tag = compilation.config_tag(
            repr(self.decode_model), self.max_slots, self.max_len,
            self.buckets, self.layout_name, self.prefill_chunk)
        fp = compilation.topology_fingerprint(tag=tag)
        for name, fn, bucket in self._aot_jobs():
            args = self._example_args(name, bucket)
            key = (name, bucket) if bucket is not None else name
            fname = f"serve_{name}" + (f"_b{bucket}" if bucket else "")
            self._aot[key] = compilation.load_or_compile(
                fn, args, directory=aot_dir, name=fname, fingerprint=fp)

    def _call_prefill(self, padded, plen, cache0=None):
        fn = self._aot.get(("prefill", int(padded.shape[1])))
        if fn is None:
            fn = self._prefill_jit
        if cache0 is None:
            cache0 = self._prefill_zero
        return fn(self.params, cache0, padded, plen)

    def _call_insert(self, small, slot, plen):
        fn = self._aot.get("insert", self._insert_jit)
        return fn(self.cache, small, slot, plen)

    def _call_step(self):
        fn = self._aot.get("step", self._step_jit)
        return fn(self.params, self.cache, self._tok, self._temp, self._keys)

    def _call_sample1(self, logits, temp, keys):
        fn = self._aot.get("sample1", self._sample1_jit)
        return fn(logits, temp, keys)

    def _call_chunk(self, toks, slot, start, nvalid, arm):
        fn = self._aot.get("chunk", self._chunk_jit)
        return fn(self.params, self.cache, toks,
                  jnp.asarray(slot, jnp.int32),
                  jnp.asarray(start, jnp.int32),
                  jnp.asarray(nvalid, jnp.int32),
                  jnp.asarray(arm, jnp.int32))

    def _call_bind(self, slot):
        """Push slot ``slot``'s host page-table row to the device —
        ONE dispatch regardless of how many pages just changed."""
        fn = self._aot.get("bind", self._bind_jit)
        row = np.asarray(self.layout.slot_pages[slot], np.int32)
        self.cache = fn(self.cache, jnp.asarray(slot, jnp.int32),
                        jnp.asarray(row))

    def _call_release(self, slot):
        fn = self._aot.get("release", self._release_jit)
        self.cache = fn(self.cache, jnp.asarray(slot, jnp.int32))

    def warmup(self) -> dict:
        """Pre-pay every compile before the first request — then rebuild
        pristine slot state, so the warmed engine is indistinguishable
        from a fresh one except that no program compiles on the serving
        path again (the ONE-decode-compile invariant holds with the
        compile moved ahead of traffic).

        Returns ``{"seconds": ..., "compiles": ...}`` (compiles == 0
        when an AOT pool or a warm persistent cache made even warmup
        free of backend compilation... the jit-cache invariant is what
        :meth:`compile_stats` reports either way)."""
        import time

        from ..obs import jaxmon

        jaxmon.install()
        c0 = jaxmon.compile_count()
        t0 = time.perf_counter()
        if self.layout_name == "paged":
            # chunk against the pristine all-unallocated page tables:
            # every write drops, every read is masked — pure compile
            self.cache, last = self._call_chunk(
                jnp.zeros((1, self.prefill_chunk), jnp.int32), 0, 0, 1, 0)
            self._call_sample1(
                last, jnp.zeros((1,), jnp.float32),
                jnp.zeros((1, 2), jnp.uint32))
            self._call_bind(0)
            self._call_release(0)
        else:
            small = last = None
            for b in self.buckets:
                small, last = self._call_prefill(
                    jnp.zeros((1, b), jnp.int32), jnp.asarray(1, jnp.int32))
            if self.prefill_chunk and self.prefill_chunk not in self.buckets:
                small, last = self._call_prefill(
                    jnp.zeros((1, self.prefill_chunk), jnp.int32),
                    jnp.asarray(1, jnp.int32))
            self._call_sample1(
                last, jnp.zeros((1,), jnp.float32),
                jnp.zeros((1, 2), jnp.uint32))
            # the splice and step donate the live slot state; the dummy
            # data they leave behind is discarded with the rebuild below
            self.cache = self._call_insert(
                small, jnp.asarray(0, jnp.int32), jnp.asarray(1, jnp.int32))
        self.cache, self._tok, self._keys = self._call_step()
        jax.block_until_ready(self._tok)
        self.cache = make_decode_cache(
            self.decode_model, self.max_slots, self.max_len)
        self._tok = jnp.zeros((self.max_slots,), jnp.int32)
        self._temp = jnp.zeros((self.max_slots,), jnp.float32)
        self._keys = jnp.zeros((self.max_slots, 2), jnp.uint32)
        return {"seconds": time.perf_counter() - t0,
                "compiles": int(jaxmon.compile_count() - c0)}

    # ---- host-side API (called by the scheduler loop thread) --------------

    def pick_bucket(self, plen: int) -> int:
        """Smallest warm bucket covering ``plen`` (jit caches stay warm)."""
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(
            f"prompt length {plen} exceeds the largest prefill bucket "
            f"({self.buckets[-1]}). Either shorten the prompt or construct "
            f"the engine with a larger bucket (buckets={self.buckets}, "
            f"max_len={self.max_len}).")

    def validate_request(self, prompt_len: int, max_new_tokens: int) -> None:
        """Admission-time shape checks — every error is actionable."""
        if prompt_len < 1:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if self.buckets:
            self.pick_bucket(prompt_len)
        if prompt_len + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
                f"= {prompt_len + max_new_tokens} exceeds the engine's slot "
                f"cache (max_len={self.max_len}). Lower max_new_tokens or "
                "rebuild the engine with a larger max_len.")
        if self.layout_name == "paged":
            need = self.layout.pages_for(prompt_len + max_new_tokens)
            total = self.layout.pool.num_blocks
            if need > total:
                raise ValueError(
                    f"request needs {need} KV blocks at its token budget "
                    f"(prompt {prompt_len} + max_new_tokens "
                    f"{max_new_tokens}, block size "
                    f"{self.layout.block_size}) but the pool only has "
                    f"{total}. Lower max_new_tokens, or rebuild the engine "
                    f"with kv_blocks >= {need}.")

    def can_admit(self, prompt: Sequence[int], max_new_tokens: int) -> bool:
        """Admission gate beyond free slots: in the paged layout a
        request is only admitted when the block pool can cover its
        WORST-CASE footprint on top of every already-admitted slot's —
        so an admitted request can always run to its budget and pool
        exhaustion surfaces as queueing, never as a stuck slot."""
        return self.layout.can_admit(prompt, max_new_tokens)

    # ---- prefill (whole-prompt and incremental) ---------------------------

    def prefill_begin(self, slot: int, tokens: Sequence[int],
                      temperature: float, key: np.ndarray,
                      max_new_tokens: Optional[int] = None,
                      rid: Optional[str] = None) -> _PrefillState:
        """Start prefilling ``tokens`` into ``slot``; the scheduler
        advances the returned state one chunk per :meth:`prefill_step`
        call (interleaving chunks with decode ticks).  ``max_new_tokens``
        sizes the paged worst-case reservation (default: the whole slot
        budget) — pass the request's real bound so the reservation
        matches what :meth:`can_admit` agreed to.  ``rid`` is the
        request's trace id (obs.reqtrace): it rides this state so
        engine-side chunk advances stay attributable to the request —
        host metadata only, never an input to a compiled program."""
        st = _PrefillState(slot, tokens, temperature, key, rid=rid)
        if self.layout_name == "paged":
            budget = (self.max_len - st.plen if max_new_tokens is None
                      else max_new_tokens)
            start = self.layout.admit(slot, st.tokens, budget)
            st.pos = start
            if start:
                # claimed prefix pages go live on device now — one
                # row-bind dispatch however long the cached prefix is
                self._call_bind(slot)
            self._host_pos[slot] = start
        else:
            st.small = self._prefill_zero
        return st

    def prefill_step(self, st: _PrefillState):
        """Advance one chunk (or, without chunking, the whole prompt).
        Returns ``(first_token | None, real_tokens, padded_tokens)`` —
        a non-None first token means prefill completed and the slot is
        armed for decode."""
        if not self.prefill_incremental:
            first, bucket = self._prefill_whole(
                st.slot, st.tokens, st.temperature, st.key)
            return first, st.plen, bucket
        chunk = self.prefill_chunk
        nvalid = min(chunk, st.plen - st.pos)
        final = st.pos + nvalid >= st.plen
        padded = np.zeros((1, chunk), np.int32)
        padded[0, :nvalid] = st.tokens[st.pos:st.pos + nvalid]
        if self.layout_name == "paged":
            if self.layout.alloc_rows(st.slot, st.pos + nvalid):
                self._call_bind(st.slot)
            # arm flips the slot_live write gate on the final chunk —
            # until then decode-tick drift writes drop for this row
            self.cache, last = self._call_chunk(
                jnp.asarray(padded), st.slot, st.pos, nvalid,
                1 if final else 0)
        else:
            start = st.pos
            if start + chunk > self.max_len:
                # a padded FINAL chunk would write past the batch-1
                # cache and dynamic_update_slice clamps the start back,
                # corrupting earlier rows — shift the window back
                # instead: re-prefilled positions rewrite identical K/V
                # (same token, same position), pad rows land in
                # [plen, max_len) where decode's own write precedes any
                # attending query (the whole-bucket padding argument)
                start = self.max_len - chunk
                padded[0] = 0
                padded[0, :st.plen - start] = st.tokens[start:st.plen]
                nvalid_w = st.pos + nvalid - start

                def rewind(path, leaf):
                    if _leaf_name(path) in ("cache_index", "pos_index"):
                        return jnp.full_like(leaf, start)
                    return leaf

                st.small = jax.tree_util.tree_map_with_path(
                    rewind, st.small)
            else:
                nvalid_w = nvalid
            st.small, last = self._call_prefill(
                jnp.asarray(padded), jnp.asarray(nvalid_w, jnp.int32),
                cache0=st.small)
        st.pos += nvalid
        st.padded += chunk
        if st.pos < st.plen:
            return None, nvalid, chunk
        # final chunk: splice (dense), arm sampling state, first token
        if self.layout_name == "dense":
            self.cache = self._call_insert(
                st.small, jnp.asarray(st.slot, jnp.int32),
                jnp.asarray(st.plen, jnp.int32))
        else:
            self.layout.register_prompt(st.slot, st.tokens)
            self._host_pos[st.slot] = st.plen
            self._decoding.add(st.slot)
        first = self._arm(st.slot, last, st.temperature, st.key)
        return first, nvalid, chunk

    def _arm(self, slot: int, last_logits, temperature: float, key) -> int:
        """Sample the first token from the prefill logits and arm the
        slot's on-device sampling state."""
        nxt, new_key = self._call_sample1(
            last_logits, jnp.asarray([temperature], jnp.float32),
            jnp.asarray(key)[None])
        first = int(np.asarray(nxt)[0])
        self._tok = self._tok.at[slot].set(first)
        self._temp = self._temp.at[slot].set(float(temperature))
        self._keys = self._keys.at[slot].set(new_key[0])
        return first

    def _prefill_whole(self, slot: int, tokens: Sequence[int],
                       temperature: float, key: np.ndarray):
        """The original dense whole-prompt path: one bucketed prefill
        spliced into the slot; returns ``(first_token, bucket)``."""
        plen = len(tokens)
        bucket = self.pick_bucket(plen)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = np.asarray(tokens, np.int32)
        small, last = self._call_prefill(
            jnp.asarray(padded), jnp.asarray(plen, jnp.int32))
        self.cache = self._call_insert(
            small, jnp.asarray(slot, jnp.int32), jnp.asarray(plen, jnp.int32))
        first = self._arm(slot, last, temperature, key)
        return first, bucket

    def prefill(self, slot: int, tokens: Sequence[int], temperature: float,
                key: np.ndarray):
        """Prefill ``tokens`` into slot ``slot`` and arm its on-device
        sampling state; returns ``(first_token, padded_tokens)``.  Runs
        every chunk back-to-back — the scheduler uses the incremental
        API instead when it wants chunks interleaved with decode."""
        st = self.prefill_begin(slot, tokens, temperature, key)
        if not self.prefill_incremental:
            return self.prefill_step(st)[0], self.pick_bucket(st.plen)
        while True:
            first, _, _ = self.prefill_step(st)
            if first is not None:
                return first, st.padded

    # ---- decode / teardown ------------------------------------------------

    def step_decode(self) -> np.ndarray:
        """One compiled step over all slots; per-slot input tokens, keys
        and temperatures live on device — the only host traffic is the
        returned ``next[S]`` (the scheduler's stop checks/streaming).
        Parked rows compute too; their output is discarded.  In the
        paged layout, each decoding slot's next write position is
        covered by a just-in-time block bind BEFORE the compiled step
        (reservation guarantees the pool can serve it)."""
        if self.layout_name == "paged":
            for slot in self._decoding:
                if self.layout.alloc_rows(slot, self._host_pos[slot] + 1):
                    self._call_bind(slot)
                self._host_pos[slot] += 1
        self.cache, self._tok, self._keys = self._call_step()
        return np.asarray(self._tok)

    def reset_slot(self, slot: int) -> None:
        """Park a freed slot: zero its cursor (so it cannot creep toward
        int32 wraparound across very long serving sessions) and its
        temperature.  Parked slots still ride the compiled step; their
        writes/outputs are masked/discarded.  The paged layout also
        returns the slot's blocks to the pool (prefix-cached blocks stay
        reclaimable) and clears its device page-table row."""
        if self.layout_name == "paged":
            self.layout.release(slot)
            self._call_release(slot)
            self._decoding.discard(slot)
            self._host_pos[slot] = 0
        else:
            def leaf(path, bg):
                name = _leaf_name(path)
                if name in ("cache_index", "pos_index"):
                    return bg.at[slot].set(jnp.zeros((), bg.dtype))
                return bg

            self.cache = jax.tree_util.tree_map_with_path(leaf, self.cache)
        self._temp = self._temp.at[slot].set(0.0)

    # ---- reporting --------------------------------------------------------

    def pool_stats(self) -> dict:
        """The layout's stats: block-pool occupancy and prefix-cache
        counters for the paged layout; both layouts report their
        ``kv_quant`` storage scenario."""
        return self.layout.stats()

    def kv_cache_bytes(self) -> dict:
        """KV HBM accounting: ``reserved`` is what the cache tensors
        occupy (measured off the live leaves); ``live`` is the fraction
        actually backing live tokens (== reserved for dense — the whole
        point of the paged layout is the gap between the two);
        ``predicted`` is the layout's own sizing model
        (:func:`..serve.cache_layout.reserved_kv_bytes` — the ONE
        source of truth admission control and the benches share),
        parity-pinned against ``reserved`` by test in BOTH layouts for
        every kv_quant scenario including the int8/fp8 scale leaves."""
        from .cache_layout import reserved_kv_bytes

        total = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(self.cache)[0]:
            # K/V rows plus their quantization scales (the scales are
            # real HBM the quantized layouts pay — counting them keeps
            # the bytes-per-token comparison honest)
            if _leaf_name(path) in ("cached_k", "cached_v",
                                    "cached_k_scale", "cached_v_scale"):
                total += leaf.size * leaf.dtype.itemsize
        model = self.model
        predicted = reserved_kv_bytes(
            self.layout, int(model.depth),
            int(model.num_kv_heads or model.num_heads),
            int(model.dim // model.num_heads),
            jnp.dtype(model.dtype).itemsize)
        out = {"reserved": total, "live": total, "predicted": predicted}
        if self.layout_name == "paged":
            s = self.layout.stats()
            frac = s["kv_blocks_active"] / max(1, s["kv_blocks_total"])
            out["live"] = int(total * frac)
        return out

    def compile_stats(self) -> dict:
        """Compile counts per program — the no-recompile steady-state
        assertion reads ``decode_compiles == 1`` after warmup (a
        ``prewarm=True`` engine satisfies it before the first request).
        An AOT engine serves through deserialized executables instead of
        the jits, so its jit cache sizes stay 0 and ``aot_programs``
        reports the loaded pool instead.  The paged layout's prefill
        program is the chunk program; its page-table maintenance
        programs (``bind``/``release``) are reported so tests can pin
        the WHOLE pool at one compile each."""
        stats = {
            "decode_compiles": _jit_cache_size(self._step_jit),
            "insert_compiles": (
                _jit_cache_size(self._insert_jit)
                if self.layout_name == "dense" else 0),
            "aot_programs": len(self._aot),
        }
        if self.layout_name == "paged":
            stats["prefill_compiles"] = _jit_cache_size(self._chunk_jit)
            stats["bind_compiles"] = _jit_cache_size(self._bind_jit)
            stats["release_compiles"] = _jit_cache_size(self._release_jit)
        else:
            stats["prefill_compiles"] = _jit_cache_size(self._prefill_jit)
        return stats
