"""The reference test suite's backbone, on an 8-device mesh (SURVEY §4):

1. distributed gradient accumulation == single-batch gradients
   (reference: check_data_parallel test/single_device.jl:6-36 and
   test_grad_syncing_in_train :66-97), and
2. after an optimizer step, the distributed result == the batched result
   and all replicas remain identical
   (reference: check_distributed_opt test/single_device.jl:99-113,
   asserts at :153-166).

Run on 8 virtual CPU devices (conftest), exactly as the driver dry-runs
multi-chip sharding.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from fluxdistributed_tpu import optim, sharding, tree
from fluxdistributed_tpu.models import SimpleCNN
from fluxdistributed_tpu.ops import logitcrossentropy
from fluxdistributed_tpu.parallel import (
    TrainState,
    make_train_step,
    make_train_step_shardmap,
)
from fluxdistributed_tpu.parallel.dp import flax_loss_fn

BATCH = 32  # divisible by 8 devices
NCLASS = 10


@pytest.fixture(scope="module")
def setup(request):
    import fluxdistributed_tpu.mesh as mesh_lib

    mesh = mesh_lib.data_mesh(8)
    model = SimpleCNN(num_classes=NCLASS)
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH, 8, 8, 3), jnp.float32)
    y = jax.nn.one_hot(
        jax.random.randint(jax.random.PRNGKey(2), (BATCH,), 0, NCLASS), NCLASS
    )
    variables = model.init(rng, x[:2], train=True)
    params = variables["params"]
    loss_fn = flax_loss_fn(model, logitcrossentropy)
    return mesh, model, params, loss_fn, {"image": x, "label": y}


def global_grads(loss_fn, params, batch):
    """Single-device global-batch gradients — the ground truth the
    reference compares against (test/single_device.jl:20,78)."""

    def lossf(p):
        return loss_fn(p, {}, batch, True)

    (loss, _), grads = jax.value_and_grad(lossf, has_aux=True)(params)
    return loss, grads


def per_shard_grads(loss_fn, params, batch, nshards):
    """Per-device gradients computed independently then host-averaged —
    the reference's sync path re-created leaf-for-leaf (train_step →
    markbuffer! → sync_buffer, src/ddp_tasks.jl:80-109)."""
    shards = []
    n = batch["image"].shape[0] // nshards
    for i in range(nshards):
        sub = {k: v[i * n : (i + 1) * n] for k, v in batch.items()}

        def lossf(p):
            return loss_fn(p, {}, sub, True)

        (_, _), g = jax.value_and_grad(lossf, has_aux=True)(params)
        shards.append(g)
    return tree.mean(shards)


def test_invariant_1_host_mean_equals_global_grad(setup):
    """Mean of per-shard grads == global-batch grad (losses are per-shard
    means of equal shards, so the mean of grads == grad of global mean)."""
    mesh, model, params, loss_fn, batch = setup
    _, gg = global_grads(loss_fn, params, batch)
    sg = per_shard_grads(loss_fn, params, batch, 8)
    tree.assert_close(sg, gg, rtol=1e-4, atol=1e-5)


def test_invariant_1_compiled_spmd_equals_global_grad(setup):
    """The compiled SPMD step's gradient (via its parameter update with
    plain SGD) matches the single-device global-batch gradient."""
    mesh, model, params, loss_fn, batch = setup
    lr = 1.0  # so p_new = p - grad, making the gradient directly readable
    opt = optim.descent(lr)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(sharding.replicate(params, mesh), opt)
    sbatch = sharding.shard_batch(batch, mesh)
    new_state, metrics = step(state, sbatch)
    implied_grad = jax.tree.map(lambda a, b: a - b, state.params, new_state.params)
    _, gg = global_grads(loss_fn, params, batch)
    tree.assert_close(implied_grad, gg, rtol=1e-4, atol=1e-5)
    gl, _ = global_grads(loss_fn, params, batch)
    assert np.isclose(float(metrics["loss"]), float(gl), rtol=1e-5)


def test_invariant_1_shardmap_pmean_equals_global_grad(setup):
    """Explicit shard_map + pmean path gives the same gradients."""
    mesh, model, params, loss_fn, batch = setup
    opt = optim.descent(1.0)
    step = make_train_step_shardmap(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(sharding.replicate(params, mesh), opt)
    sbatch = sharding.shard_batch(batch, mesh)
    new_state, metrics = step(state, sbatch)
    implied_grad = jax.tree.map(lambda a, b: a - b, state.params, new_state.params)
    _, gg = global_grads(loss_fn, params, batch)
    tree.assert_close(implied_grad, gg, rtol=1e-4, atol=1e-5)


def test_invariant_2_update_matches_batched_and_replicas_identical(setup):
    """Distributed optimizer step == single-device batched step, and every
    device holds bit-identical parameters afterwards (the reference's
    asserts at test/single_device.jl:153-166)."""
    mesh, model, params, loss_fn, batch = setup
    opt = optim.momentum(0.01, 0.9)

    # single-device reference update
    _, gg = global_grads(loss_fn, params, batch)
    ref_params, ref_st = opt.apply(params, gg, opt.init(params), 0)

    step = make_train_step(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(sharding.replicate(params, mesh), opt)
    new_state, _ = step(state, sharding.shard_batch(batch, mesh))

    tree.assert_close(new_state.params, ref_params, rtol=1e-4, atol=1e-5)
    tree.assert_close(new_state.opt_state, ref_st, rtol=1e-4, atol=1e-5)

    # replicas identical: every per-device copy of every leaf is equal
    for leaf in jax.tree.leaves(new_state.params):
        per_dev = [np.asarray(s.data) for s in leaf.addressable_shards]
        for d in per_dev[1:]:
            np.testing.assert_array_equal(per_dev[0], d)
    assert int(new_state.step) == 1


def test_multi_step_consistency(setup):
    """Several steps of compiled DP == several steps of single-device
    training (momentum state carried through)."""
    mesh, model, params, loss_fn, batch = setup
    opt = optim.momentum(0.05, 0.9)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(sharding.replicate(params, mesh), opt)

    ref_p, ref_st = params, opt.init(params)
    for i in range(3):
        _, gg = global_grads(loss_fn, ref_p, batch)
        ref_p, ref_st = opt.apply(ref_p, gg, ref_st, i)
        state, _ = step(state, sharding.shard_batch(batch, mesh))

    tree.assert_close(state.params, ref_p, rtol=1e-4, atol=1e-5)
