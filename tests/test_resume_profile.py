"""Checkpoint resume + step-timing/profiling — gaps the reference left
open (SURVEY §5: save-only checkpoints, no resume, no profiling; its only
timing hook is ``@timed`` in dead code, src/test.jl:52).
"""

import glob
import os

import numpy as np
import pytest

# tier-2 (slow): checkpoint/resume trainer runs — the tier-1 iteration loop must fit the
# 870s verify window (ROADMAP); CI's slow job still runs this file
pytestmark = pytest.mark.slow

from fluxdistributed_tpu import mesh as mesh_lib, optim, tree as tree_lib
from fluxdistributed_tpu.data import SyntheticDataset
from fluxdistributed_tpu.models import SimpleCNN
from fluxdistributed_tpu.train import (
    prepare_training,
    restore_training,
    train,
)
from fluxdistributed_tpu.train.logging import NullLogger


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.data_mesh(8)


def _task(mesh, cycles=4, seed=0):
    ds = SyntheticDataset(nsamples=64, nclasses=4, shape=(16, 16, 3))
    return prepare_training(
        SimpleCNN(num_classes=4), ds, optim.momentum(0.05, 0.9),
        mesh=mesh, batch_size=16, cycles=cycles, seed=seed,
    )


def test_resume_restores_full_state(mesh, tmp_path):
    from fluxdistributed_tpu.train import latest_step, save_checkpoint

    ckdir = str(tmp_path / "ck")
    task = _task(mesh)
    train(task, print_every=0, eval_every=0, logger=NullLogger(),
          checkpoint_dir=ckdir, checkpoint_every=2)
    assert int(task.state.step) == 4
    # in-loop cadence: checkpoint_every=2 → saved at cycle j=2 = step 3
    assert latest_step(ckdir) == 3
    # save the final state too; resume must pick this (the latest)
    save_checkpoint(task.state, ckdir, int(task.state.step))
    want = {
        "params": tree_lib.to_host(task.state.params),
        "opt": tree_lib.to_host(task.state.opt_state),
    }

    fresh = _task(mesh, seed=99)  # different init — must be overwritten
    restore_training(fresh, ckdir)
    assert int(fresh.state.step) == 4
    # bit-exact round-trip of params AND optimizer momentum buffers
    tree_lib.assert_close(tree_lib.to_host(fresh.state.params), want["params"],
                          rtol=0, atol=0)
    tree_lib.assert_close(tree_lib.to_host(fresh.state.opt_state), want["opt"],
                          rtol=0, atol=0)
    # and training continues from the restored state on the mesh
    train(fresh, print_every=0, eval_every=0, logger=NullLogger())
    assert int(fresh.state.step) == 8


class _CaptureLogger:
    def __init__(self):
        self.metrics = []

    def log(self, m, step):
        self.metrics.append((step, dict(m)))

    def info(self, msg):
        pass


def test_tp_sharded_resume(tmp_path):
    """spmd='tp' checkpoints save model-sharded and restore model-sharded
    (the abstract-target path), then training continues."""
    from jax.sharding import PartitionSpec as P

    from fluxdistributed_tpu.data import SyntheticTextDataset
    from fluxdistributed_tpu.models import lm_loss_fn, lm_tiny
    from fluxdistributed_tpu.train import restore_training

    mesh = mesh_lib.make_mesh({"data": 2, "model": 4})
    model = lm_tiny(vocab=32, dtype=np.float32)
    ds = SyntheticTextDataset(vocab=32, seqlen=32)

    def mk(cycles):
        return prepare_training(
            model, ds, optim.adam(1e-3), mesh=mesh, batch_size=16,
            cycles=cycles, loss_fn=lm_loss_fn(model), topk=(), spmd="tp",
        )

    task = mk(4)
    train(task, print_every=0, eval_every=0, logger=NullLogger(),
          checkpoint_dir=str(tmp_path), checkpoint_every=2)

    task2 = restore_training(mk(3), str(tmp_path))
    emb = task2.state.params["embed"]["embedding"]
    assert emb.sharding.spec == P("model", None)
    assert int(task2.state.step) > 0
    train(task2, print_every=0, eval_every=0, logger=NullLogger())


def test_async_checkpoint_commits(mesh, tmp_path):
    """block=False saves must survive state mutation after the call (the
    device→host snapshot is synchronous) and be fully on disk after
    wait_for_pending — the train loop's contract."""
    import jax

    from fluxdistributed_tpu.train.checkpoint import (
        load_checkpoint,
        save_checkpoint,
        wait_for_pending,
    )

    task = _task(mesh)
    snap = tree_lib.to_host(task.state.params)
    save_checkpoint(task.state, str(tmp_path), 0, block=False)
    # mutate state immediately: the async write must hold the snapshot
    task.state = task.state.replace(
        params=jax.tree.map(lambda x: x * 0.0, task.state.params)
    )
    wait_for_pending()
    restored = load_checkpoint(str(tmp_path), step=0)
    for a, b in zip(jax.tree.leaves(snap), jax.tree.leaves(restored["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_train_loop_async_checkpoint(mesh, tmp_path):
    """train() uses async saves; files must be complete when train returns."""
    from fluxdistributed_tpu.train import latest_step

    task = _task(mesh, cycles=5)
    train(task, print_every=0, eval_every=0, logger=NullLogger(),
          checkpoint_dir=str(tmp_path), checkpoint_every=2)
    assert latest_step(str(tmp_path)) is not None


def test_throughput_metrics_logged(mesh):
    task = _task(mesh, cycles=6)
    logger = _CaptureLogger()
    train(task, print_every=2, eval_every=0, logger=logger)
    rates = [m for _, m in logger.metrics if "images_per_sec" in m]
    assert rates, "expected steps/images-per-sec at the print cadence"
    assert all(m["images_per_sec"] > 0 and m["steps_per_sec"] > 0 for m in rates)


def test_profiler_trace_written(mesh, tmp_path):
    pdir = str(tmp_path / "trace")
    task = _task(mesh, cycles=4)
    train(task, print_every=0, eval_every=0, logger=NullLogger(),
          profile_dir=pdir, profile_start=1, profile_steps=2)
    traces = glob.glob(os.path.join(pdir, "**", "*.trace.json.gz"), recursive=True) + \
        glob.glob(os.path.join(pdir, "**", "*.xplane.pb"), recursive=True)
    assert traces, f"no trace files under {pdir}"
