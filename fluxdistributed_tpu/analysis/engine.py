"""fdtpu-lint scanner: walk source trees, run the AST rules, diff the
baseline.

The scanner is the jax-free half of the suite (the jaxpr layer lives in
:mod:`analysis.jaxpr_checks`): it parses every ``.py`` file under the
given roots with stdlib ``ast`` and runs the :data:`rules_ast.AST_RULES`
registry over each module.  Default roots are the package itself plus
``bin/`` — the code that runs on hardware; tests and benchmarks are
deliberately out of scope (they host-branch and wall-clock freely, by
design).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence, Set

from .findings import Finding
from .rules_ast import AST_RULES, ModuleContext, run_ast_rules

__all__ = [
    "repo_root",
    "default_roots",
    "iter_py_files",
    "scan_file",
    "scan_paths",
    "scan_repo",
]

#: directories never descended into
_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".venv", "venv",
              "site", "build", "dist"}


def repo_root() -> str:
    """The repository root — the parent of the ``fluxdistributed_tpu``
    package directory.  Findings report paths relative to it."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def default_roots() -> List[str]:
    """What a bare ``bin/lint.py`` scans: the package + the CLI entry
    points.  ``bench.py`` rides along — its JSON line is the hardware
    round's record of truth and must not silently rot."""
    root = repo_root()
    out = [os.path.join(root, "fluxdistributed_tpu"),
           os.path.join(root, "bin"),
           os.path.join(root, "bench.py")]
    return [p for p in out if os.path.exists(p)]


def iter_py_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    seen: Set[str] = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            if p.endswith(".py") and p not in seen:
                seen.add(p)
                files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                full = os.path.join(dirpath, fn)
                if fn.endswith(".py") and full not in seen:
                    seen.add(full)
                    files.append(full)
    return files


def _relpath(path: str, root: Optional[str] = None) -> str:
    root = root or repo_root()
    try:
        rel = os.path.relpath(path, root)
    except ValueError:  # different drive (windows)
        rel = path
    if rel.startswith(".."):
        rel = path
    return rel.replace(os.sep, "/")


def scan_file(path: str, root: Optional[str] = None,
              rules=None) -> List[Finding]:
    """AST-lint one file.  A file that does not parse yields the single
    finding ``FDT000`` (parse-error) — a broken file must fail the lint
    gate, not crash it."""
    rel = _relpath(path, root)
    try:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as e:
        line = getattr(e, "lineno", 0) or 0
        return [Finding(
            rule="FDT000", severity="error", file=rel, line=line,
            message=f"file does not parse: {type(e).__name__}: {e}",
            hint="fix the syntax error", detail=type(e).__name__)]
    ctx = ModuleContext(path, rel, source, tree)
    return run_ast_rules(ctx, rules)


def scanned_files(paths: Optional[Sequence[str]] = None,
                  root: Optional[str] = None) -> List[str]:
    """Repo-relative paths a scan of ``paths`` (default: the full
    default roots) covers — including clean files that yield no
    findings.  ``--update-baseline`` uses this to know which baseline
    entries the scan could have re-observed."""
    return [_relpath(f, root)
            for f in iter_py_files(paths or default_roots())]


def scan_paths(paths: Sequence[str], root: Optional[str] = None,
               rules=None) -> List[Finding]:
    out: List[Finding] = []
    for f in iter_py_files(paths):
        out.extend(scan_file(f, root=root, rules=rules))
    out.sort(key=lambda f: (f.file, f.line, f.rule))
    return out


def scan_repo(rules=None) -> List[Finding]:
    """The full default AST scan (package + bin + bench.py)."""
    return scan_paths(default_roots(), rules=rules)
