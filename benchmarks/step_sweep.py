#!/usr/bin/env python
"""Train-step configuration sweep for the ResNet-50 bench.

Measures steady-state img/s for combinations of model/input dtype
variants and XLA flags.  XLA flags bind at backend init, so the parent
re-execs itself (``--one``) with each configuration's environment and
collects one JSON line per child.

Run on the real chip:  python benchmarks/step_sweep.py
Child mode (internal): python benchmarks/step_sweep.py --one '<json>'
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

# bench.py (the shared timing protocol) lives at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONFIGS = [
    {"name": "baseline-bf16", "env": {}},
    {"name": "bn-f32", "env": {"SWEEP_BN_F32": "1"}},
    {"name": "input-f32", "env": {"SWEEP_INPUT_F32": "1"}},
    {"name": "latency-hiding-sched", "env": {
        "SWEEP_XLA_FLAGS": "--xla_tpu_enable_latency_hiding_scheduler=true"}},
    {"name": "no-donate", "env": {"SWEEP_NO_DONATE": "1"}},
    {"name": "batch-512", "env": {"SWEEP_BATCH": "512"}},
    {"name": "grad-accum-2", "env": {"SWEEP_ACCUM": "2", "SWEEP_BATCH": "512"}},
]


def measure_one() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import fluxdistributed_tpu as fd
    from fluxdistributed_tpu import optim, sharding
    from fluxdistributed_tpu.models import resnet50
    from fluxdistributed_tpu.parallel import TrainState, make_train_step
    from fluxdistributed_tpu.parallel.dp import flax_loss_fn

    batch = int(os.environ.get("SWEEP_BATCH", "256"))
    size = int(os.environ.get("SWEEP_SIZE", "224"))
    accum = int(os.environ.get("SWEEP_ACCUM", "1"))
    donate = not os.environ.get("SWEEP_NO_DONATE")
    bn_f32 = bool(os.environ.get("SWEEP_BN_F32"))
    input_f32 = bool(os.environ.get("SWEEP_INPUT_F32"))

    mesh = fd.data_mesh()
    # bn-f32 variant: convs stay bf16, BatchNorm computes in f32
    model = resnet50(
        num_classes=1000,
        norm_dtype=jnp.float32 if bn_f32 else None,
    )

    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (batch, size, size, 3)).astype(np.float32)
    y = rng.integers(0, 1000, batch)
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    params = variables["params"]
    mstate = {k: v for k, v in variables.items() if k != "params"}
    loss_fn = flax_loss_fn(model, fd.logitcrossentropy)
    opt = optim.momentum(0.1, 0.9)
    step = make_train_step(loss_fn, opt, mesh, donate=donate, accum_steps=accum)
    state = TrainState.create(
        sharding.replicate(params, mesh), opt,
        model_state=sharding.replicate(mstate, mesh),
    )
    xb = x if input_f32 else x.astype(jnp.bfloat16)
    b = sharding.shard_batch(
        {"image": xb, "label": np.asarray(fd.onehot(y, 1000))}, mesh
    )

    import bench

    dt, _ = bench.time_compiled_step(
        step, state, b, target_seconds=float(os.environ.get("SWEEP_SECONDS", "2.0"))
    )
    return {
        "img_per_sec_per_chip": round(batch / dt / jax.device_count(), 1),
        "step_ms": round(dt * 1e3, 2),
        "batch": batch,
        "platform": jax.devices()[0].platform,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--one", default=None)
    args = ap.parse_args()
    if args.one is not None:
        print(json.dumps(measure_one()))
        return

    results = []
    for cfg in CONFIGS:
        env = {**os.environ, **cfg["env"]}
        # APPEND sweep flags to pre-existing XLA_FLAGS so the row stays
        # comparable to the others (which inherit the environment's flags)
        extra = env.pop("SWEEP_XLA_FLAGS", None)
        if extra:
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + extra).strip()
        try:
            # generous timeout — a timeout SIGKILL of a TPU child can
            # leave the device grant wedged for every later config, so
            # this is a last resort, not a scheduling tool
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one", "{}"],
                env=env, capture_output=True, text=True, timeout=1800,
            )
        except subprocess.TimeoutExpired as e:
            results.append({"config": cfg["name"], "error": "timeout",
                            "stderr": (e.stderr or "")[-300:]})
            print(json.dumps(results[-1]), flush=True)
            continue
        lines = p.stdout.strip().splitlines()
        r = None
        if lines:
            try:
                r = json.loads(lines[-1])
            except json.JSONDecodeError:
                pass
        if r is None or p.returncode != 0:
            r = {"error": f"rc={p.returncode}",
                 "stderr": p.stderr.strip()[-300:], **(r or {})}
        results.append({"config": cfg["name"], **r})
        print(json.dumps(results[-1]), flush=True)
    print(json.dumps({"sweep": results}))


if __name__ == "__main__":
    main()
