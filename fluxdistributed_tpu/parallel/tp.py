"""Tensor parallelism: parameter sharding over a ``model`` mesh axis.

Net-new scope beyond the reference (whole-replica models only —
``gpu(resnet)`` src/ddp_tasks.jl:275; SURVEY §2 "TP: NO"), built the
TPU-idiomatic way: params get ``NamedSharding``s over a 2-D
``(data, model)`` mesh and GSPMD inserts the collectives — there is no
hand-written all-gather/reduce-scatter in the training step.  The same
``TrainState``/optimizer/loss machinery as the DP path is reused; TP is
purely a placement change.

Sharding rules follow the Megatron pattern for transformers: QKV
projection column-sharded over heads, attention output row-sharded, MLP
up-projection column-sharded, down-projection row-sharded — so each
block needs exactly two all-reduces (inserted automatically as the
transpose of the row-sharded matmuls).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import mesh as mesh_lib
from ..optim import Optimizer
from .dp import TrainState

Pytree = Any

__all__ = [
    "param_specs",
    "broadcast_prefix",
    "state_specs",
    "shard_state",
    "vit_tp_rules",
    "lm_tp_rules",
    "make_train_step_tp",
]


def _is_spec(x) -> bool:
    return isinstance(x, P)


def param_specs(params: Pytree, rule: Callable[[str, Any], P]) -> Pytree:
    """Build a PartitionSpec tree by applying ``rule(path, leaf)`` to
    every param leaf.  ``path`` is '/'-joined (e.g.
    ``block0/MultiHeadAttention_0/qkv/kernel``)."""

    def f(kp, leaf):
        path = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in kp)
        return rule(path, leaf)

    return jax.tree_util.tree_map_with_path(f, params)


def broadcast_prefix(specs: Pytree, tree: Pytree) -> Pytree:
    """Broadcast a prefix tree of PartitionSpecs over a deeper tree.

    Optimizer states mirror the param tree but may nest extra structure
    per param (Adam's ``(m, v)`` tuples); each param's spec is applied to
    every array in its state subtree.
    """
    treedef = jax.tree.structure(specs, is_leaf=_is_spec)
    subtrees = treedef.flatten_up_to(tree)
    leaves = jax.tree.leaves(specs, is_leaf=_is_spec)
    mapped = [jax.tree.map(lambda _, s=s: s, sub) for s, sub in zip(leaves, subtrees)]
    return jax.tree.unflatten(treedef, mapped)


def state_specs(state: TrainState, p_specs: Pytree) -> TrainState:
    """Spec tree matching a ``TrainState``: params per ``p_specs``, opt
    state following its param, everything else replicated."""
    return TrainState(
        params=p_specs,
        opt_state=broadcast_prefix(p_specs, state.opt_state),
        model_state=jax.tree.map(lambda _: P(), state.model_state),
        step=P(),
    )


def shard_state(state: TrainState, mesh: Mesh, p_specs: Pytree) -> TrainState:
    """``device_put`` a TrainState onto the mesh per the spec tree.

    Leaves are copied first (``sharding.unaliased``) so donating the
    sharded state cannot delete the caller's source arrays."""
    from ..sharding import unaliased

    specs = state_specs(state, p_specs)

    def put(x, s):
        if x is None:
            return None
        return jax.device_put(unaliased(x), NamedSharding(mesh, s))

    return jax.tree.map(put, state, specs, is_leaf=lambda x: x is None)


def vit_tp_rules(model_axis: str = mesh_lib.MODEL_AXIS) -> Callable[[str, Any], P]:
    """Megatron-style sharding rules for ``models.vit.ViT`` param paths.

    qkv kernel  [dim, 3, heads, head_dim] → heads sharded (column)
    out kernel  [heads, head_dim, dim]    → heads sharded (row)
    MLP Dense_0 [dim, mlp_dim]            → mlp_dim sharded (column)
    MLP Dense_1 [mlp_dim, dim]            → mlp_dim sharded (row)
    Everything else (norms, patch embed, head, biases of row-sharded
    layers) replicated.
    """

    def rule(path: str, leaf) -> P:
        if path.endswith("qkv/kernel"):
            return P(None, None, model_axis, None)
        if path.endswith("qkv/bias"):
            return P(None, model_axis, None)
        if path.endswith("out/kernel"):
            return P(model_axis, None, None)
        if "MlpBlock" in path and path.endswith("Dense_0/kernel"):
            return P(None, model_axis)
        if "MlpBlock" in path and path.endswith("Dense_0/bias"):
            return P(model_axis)
        if "MlpBlock" in path and path.endswith("Dense_1/kernel"):
            return P(model_axis, None)
        return P()

    return rule


def lm_tp_rules(
    model_axis: str = mesh_lib.MODEL_AXIS, shard_vocab: bool = True
) -> Callable[[str, Any], P]:
    """Megatron-style rules for ``models.transformer_lm.TransformerLM``.

    Same block pattern as :func:`vit_tp_rules` (qkv column-sharded over
    heads, attention out row-sharded, MLP up column-/down row-sharded;
    DecoderBlock's MLP is plain ``Dense_0``/``Dense_1``), plus the LM
    embedding: ``embed/embedding [vocab, dim]`` vocab-sharded (Megatron's
    parallel vocab embedding — with tied embeddings the output
    projection's logits come out vocab-sharded and GSPMD all-gathers at
    the f32 log-softmax).  Requires heads, mlp_dim and (if
    ``shard_vocab``) vocab divisible by the model-axis size.
    """

    def rule(path: str, leaf) -> P:
        if path.endswith("embed/embedding"):
            return P(model_axis, None) if shard_vocab else P()
        if path.endswith("qkv/kernel"):
            return P(None, None, model_axis, None)
        if path.endswith("qkv/bias"):
            return P(None, model_axis, None)
        # GQA layout (num_kv_heads set): separate q [d, Hq, hd] and
        # kv [d, 2, Hkv, hd] projections, both column-sharded over heads
        # (needs Hkv % model_axis == 0; the ordering matters — "qkv/"
        # already returned above, so "kv/" cannot swallow it)
        if path.endswith("kv/kernel"):
            return P(None, None, model_axis, None)
        if path.endswith("kv/bias"):
            return P(None, model_axis, None)
        if path.endswith("q/kernel"):
            return P(None, model_axis, None)
        if path.endswith("q/bias"):
            return P(model_axis, None)
        if path.endswith("out/kernel"):
            return P(model_axis, None, None)
        if path.endswith("head/kernel"):  # untied output head
            return P(None, model_axis)
        if path.endswith("head/bias"):  # column-parallel bias follows output dim
            return P(model_axis)
        if path.endswith("Dense_0/kernel"):
            return P(None, model_axis)
        if path.endswith("Dense_0/bias"):
            return P(model_axis)
        if path.endswith("Dense_1/kernel"):
            return P(model_axis, None)
        # SwiGLU MLP (mlp="swiglu"): gate/up column-parallel, down
        # row-parallel — Megatron's pairing for gated MLPs (biasless)
        if path.endswith("gate/kernel") or path.endswith("up/kernel"):
            return P(None, model_axis)
        if path.endswith("down/kernel"):
            return P(model_axis, None)
        return P()

    return rule


def make_train_step_tp(
    loss_fn: Callable,
    optimizer: Optimizer,
    mesh: Mesh,
    p_specs: Pytree,
    state: TrainState,
    data_axis: str = mesh_lib.DATA_AXIS,
    donate: bool = True,
):
    """Compile a train step with tensor-parallel parameter shardings.

    Identical step semantics to ``make_train_step`` (global-batch mean
    loss → implicit grad all-reduce → functional optimizer update); only
    the shardings differ: params/opt-state per ``p_specs`` over the
    ``model`` axis, batch over ``data_axis``.  ``state`` is needed only
    for its tree structure (to spec the optimizer state).
    """
    specs = state_specs(state, p_specs)
    to_shardings = lambda t: jax.tree.map(
        lambda s: NamedSharding(mesh, s), t, is_leaf=_is_spec
    )
    state_shardings = to_shardings(specs)
    batch_sharding = NamedSharding(mesh, P(data_axis))

    def step(state: TrainState, batch):
        def lossf(params):
            return loss_fn(params, state.model_state, batch, True)

        (loss, (new_mstate, _)), grads = jax.value_and_grad(lossf, has_aux=True)(
            state.params
        )
        new_params, new_opt = optimizer.apply(
            state.params, grads, state.opt_state, state.step
        )
        new_state = TrainState(
            params=new_params,
            opt_state=new_opt,
            model_state=new_mstate,
            step=state.step + 1,
        )
        return new_state, {"loss": loss}

    return jax.jit(
        step,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=(state_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,) if donate else (),
    )
