"""FDT106 negative: convention-conforming (or out-of-scope) names."""

METRIC_PREFIX = "fdtpu_serve_"


def _suffix():
    return "fdtpu_dynamic_total"


def register(reg):
    reg.counter("fdtpu_serve_requests_total")
    reg.gauge("fdtpu_queue_depth")
    reg.histogram("fdtpu_train_step_seconds")
    reg.counter(_suffix())  # non-literal first arg: out of scope
    reg.counter(METRIC_PREFIX + "prefill_tokens")  # resolved, conforming
    reg.gauge(f"{METRIC_PREFIX}active_slots")  # f-string, conforming


def register_aliased(reg):
    r, p = reg, METRIC_PREFIX
    r.counter(p + "decode_tokens")  # alias chain resolves, conforming
    for stem in ("queue_wait", "tbt"):  # loop target: dynamic, skipped
        r.gauge(p + stem + "_p50")


def register_param(reg, prefix):
    # a function parameter never resolves — even if a module constant
    # shares its name elsewhere, the arg poisons it
    reg.counter(prefix + "whatever")


REBOUND = "fdtpu_"
REBOUND += "serve-"  # AugAssign poisons the name: stale value must not


def register_rebound(reg):
    # ...resolve here and mask the actually-bad registered name
    reg.counter(REBOUND + "total")
