from .logging import ConsoleLogger, Logger, NullLogger, current_logger, with_logger
from .trainer import (
    TrainTask,
    evaluate,
    prepare_training,
    restore_training,
    resume_training,
    train,
)
from .checkpoint import (
    clear_resume_manifest,
    latest_step,
    load_checkpoint,
    load_checkpoint_elastic,
    read_resume_manifest,
    save_checkpoint,
    wait_for_pending,
    write_resume_manifest,
)
from .guard import GuardConfig, GuardHalt, TrainGuard, replay_item
from .model_selection import (
    SelectionTask,
    prepare_model_selection,
    train_model_selection,
)

__all__ = [
    "GuardConfig",
    "GuardHalt",
    "TrainGuard",
    "replay_item",
    "ConsoleLogger",
    "Logger",
    "NullLogger",
    "current_logger",
    "with_logger",
    "TrainTask",
    "evaluate",
    "prepare_training",
    "restore_training",
    "resume_training",
    "train",
    "save_checkpoint",
    "wait_for_pending",
    "load_checkpoint",
    "load_checkpoint_elastic",
    "latest_step",
    "read_resume_manifest",
    "write_resume_manifest",
    "clear_resume_manifest",
    "SelectionTask",
    "prepare_model_selection",
    "train_model_selection",
]
