"""Trainer supervisor (bin/supervise.py) — classification + restarts.

Fast tier drives the Supervisor against FAKE child processes (tiny
python scripts + a test-owned metrics endpoint), so every exit class —
done / preempted / crashed / stalled / escalated / halted — and the
argv-rewrite rules are proven in seconds with no jax in the child.
The slow tier runs the real thing: ``bin/supervise.py --smoke``, a
driver run with an injected NaN (guard-quarantined) and a hang
(supervisor-SIGKILLed + resumed) that must still COMPLETE.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import textwrap

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "bin"))
import supervise  # noqa: E402

from fluxdistributed_tpu.faults import HALTED_RC, PREEMPTED_RC  # noqa: E402
from fluxdistributed_tpu.obs import MetricsServer  # noqa: E402
from fluxdistributed_tpu.obs.metrics import Registry  # noqa: E402

REPO = str(pathlib.Path(__file__).resolve().parents[1])


def write_child(tmp_path, body: str) -> str:
    """A fake child script; ``marker`` (argv[1]) distinguishes the
    first episode from restarts."""
    path = tmp_path / "child.py"
    path.write_text(textwrap.dedent(body))
    return str(path)


def run_supervisor(cmd, tmp_path, **kw):
    led = tmp_path / "ledger.json"
    kw.setdefault("verbose", False)
    kw.setdefault("poll_interval", 0.05)
    kw.setdefault("backoff", 0.01)
    sup = supervise.Supervisor(cmd, ledger=str(led), **kw)
    rc = sup.run()
    return rc, json.loads(led.read_text())


def classes(ledger):
    return [e["class"] for e in ledger["episodes"]]


# ---------------------------------------------------------------------------
# exit classification + argv rewrite (fake children)
# ---------------------------------------------------------------------------


def test_preempted_then_done_appends_resume_strips_fault_plan(tmp_path):
    child = write_child(tmp_path, """
        import os, sys
        marker = sys.argv[1]
        if not os.path.exists(marker):
            open(marker, "w").write("x")
            sys.exit(75)
        sys.exit(0)
    """)
    cmd = [sys.executable, child, str(tmp_path / "m"),
           "--checkpoint-dir", "ck", "--fault-plan", "{}"]
    rc, led = run_supervisor(cmd, tmp_path)
    assert rc == 0 and led["completed"]
    assert classes(led) == ["preempted", "done"]
    assert led["resumes"] == 1 and led["restarts"] == 0
    ep2 = led["episodes"][1]["argv"]
    assert "--resume" in ep2, "restart must resume from the checkpoint"
    assert "--fault-plan" not in ep2, (
        "an injected fault is one occurrence of weather, not a curse "
        "on every successor")


def test_keep_fault_plan_flag(tmp_path):
    child = write_child(tmp_path, """
        import os, sys
        marker = sys.argv[1]
        if not os.path.exists(marker):
            open(marker, "w").write("x")
            sys.exit(75)
        sys.exit(0)
    """)
    cmd = [sys.executable, child, str(tmp_path / "m"), "--fault-plan", "{}"]
    rc, led = run_supervisor(cmd, tmp_path, keep_fault_plan=True)
    assert rc == 0
    assert "--fault-plan" in led["episodes"][1]["argv"]
    # no --checkpoint-dir in argv -> no --resume appended (nothing to
    # resume from)
    assert "--resume" not in led["episodes"][1]["argv"]


def test_crash_restarts_bounded_with_backoff(tmp_path):
    child = write_child(tmp_path, "import sys; sys.exit(3)\n")
    rc, led = run_supervisor([sys.executable, child], tmp_path,
                             max_restarts=2)
    assert rc == 3
    assert classes(led) == ["crashed"] * 3  # first run + 2 restarts
    assert led["result"] == "restart_budget_exhausted"
    assert not led["completed"]
    assert all(e["action"] != "stop" for e in led["episodes"][:-1])


def test_guard_halt_rc_stops_immediately(tmp_path):
    child = write_child(tmp_path, f"import sys; sys.exit({HALTED_RC})\n")
    rc, led = run_supervisor([sys.executable, child], tmp_path)
    assert rc == HALTED_RC
    assert classes(led) == ["halted"]
    assert led["result"] == "halted" and not led["completed"]


def test_resume_budget_bounded(tmp_path):
    child = write_child(tmp_path, f"import sys; sys.exit({PREEMPTED_RC})\n")
    rc, led = run_supervisor([sys.executable, child], tmp_path,
                             max_resumes=2)
    assert rc == PREEMPTED_RC
    assert classes(led) == ["preempted"] * 3
    assert led["result"] == "resume_budget_exhausted"


# ---------------------------------------------------------------------------
# heartbeat watching (fake child + test-owned metrics endpoint)
# ---------------------------------------------------------------------------


@pytest.fixture()
def metrics_endpoint():
    reg = Registry()
    srv = MetricsServer(registry=reg)
    srv.start(host="127.0.0.1", port=0)
    yield reg, srv.port
    srv.stop()


STALL_CHILD = """
    import os, sys, time
    marker, port = sys.argv[1], sys.argv[2]
    if not os.path.exists(marker):
        open(marker, "w").write("x")
        print(f"metrics: http://0.0.0.0:{port}/metrics (+ /healthz)",
              flush=True)
        time.sleep(120)  # wedged: steps counter never moves again
    sys.exit(0)
"""


def test_stalled_child_is_sigkilled_and_restarted(tmp_path,
                                                  metrics_endpoint):
    reg, port = metrics_endpoint
    reg.counter("fdtpu_train_steps_total", "x").inc(3)
    child = write_child(tmp_path, STALL_CHILD)
    cmd = [sys.executable, child, str(tmp_path / "m"), str(port)]
    rc, led = run_supervisor(cmd, tmp_path, stall_timeout=1.0,
                             startup_grace=10.0)
    assert rc == 0 and led["completed"]
    assert classes(led) == ["stalled", "done"]
    # the episode recorded what it saw before the kill
    assert led["episodes"][0]["steps"] == 3
    assert "fdtpu_train_steps_total" in led["episodes"][0]["counters"]


def test_watchdog_escalation_triggers_kill(tmp_path, metrics_endpoint):
    """The wedged-collective signal: steps may look merely slow, but an
    escalation tick means the in-process watchdog declared the loop
    dead — the supervisor kills on it without waiting out the stall
    timeout."""
    reg, port = metrics_endpoint
    steps = reg.counter("fdtpu_train_steps_total", "x")
    steps.inc(1)
    esc = reg.counter("fdtpu_watchdog_escalations_total", "x")
    child = write_child(tmp_path, STALL_CHILD)
    cmd = [sys.executable, child, str(tmp_path / "m"), str(port)]

    import threading
    import time as _time

    def tick():
        _time.sleep(0.7)
        esc.inc()

    threading.Thread(target=tick, daemon=True).start()
    rc, led = run_supervisor(cmd, tmp_path, stall_timeout=30.0,
                             startup_grace=10.0)
    assert rc == 0
    assert classes(led) == ["escalated", "done"]
    assert led["episodes"][0]["wall_seconds"] < 10


def test_metrics_parsing_helpers():
    text = ("# HELP x y\n# TYPE x counter\n"
            "fdtpu_train_steps_total 7\n"
            'fdtpu_fault_injected_total{site="a"} 2\n'
            'fdtpu_fault_injected_total{site="b"} 3\n'
            "not a number nan_is_fine nope\n")
    m = supervise.parse_metrics(text)
    assert supervise.series_value(m, "fdtpu_train_steps_total") == 7
    assert supervise.series_value(m, "fdtpu_fault_injected_total") == 5
    assert supervise.series_value(m, "missing") == 0


# ---------------------------------------------------------------------------
# the real thing (slow tier; CI runs the same gate as a fast-job step)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_supervise_smoke_e2e(tmp_path):
    """NaN at step 2 -> guard quarantine; hang at step 5 -> supervisor
    SIGKILL + --resume; the run COMPLETES with zero human input."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    ledger = tmp_path / "ledger.json"
    p = subprocess.run(
        [sys.executable, os.path.join("bin", "supervise.py"),
         "--smoke", "--quiet", "--ledger", str(ledger)],
        capture_output=True, text=True, timeout=540, env=env, cwd=REPO)
    assert p.returncode == 0, (p.stdout[-2000:], p.stderr[-2000:])
    led = json.loads(ledger.read_text())
    assert led["completed"]
    cls = classes(led)
    assert cls[-1] == "done" and any(
        c in ("stalled", "escalated") for c in cls), cls
