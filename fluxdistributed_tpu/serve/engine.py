"""Slot-based continuous-batching engine for ``TransformerLM``.

The ROADMAP's inference half ("serve heavy traffic") needs many
concurrent requests per chip, but per-request Python loops throw away
exactly what makes TPUs fast: a small set of fixed-shape compiled XLA
programs (arXiv:1810.09868's core lesson).  This engine serves ANY
number of requests through exactly two jitted programs plus a splice:

* **Bucketed prefill** — a batch-1 scalar-index decode forward over the
  prompt padded up to a shape bucket ({128, 512, 2048} by default), so
  the jit cache holds one compiled prefill per bucket and stays warm no
  matter what prompt lengths arrive.  Right-padding is safe by
  construction: a position's cache slot is a function of the position
  alone, the causal mask admits only positions ≤ the query's, and every
  pad entry is overwritten by the real token for its position before it
  could ever become attendable.
* **Fixed-slot decode** — ONE single-token step over all ``max_slots``
  cache rows of a ``slot_decode=True`` model (per-slot cursors, see
  models/transformer_lm.py), compiled once.  Finished requests free
  their slot; admissions splice a prefilled batch-1 cache into a free
  row mid-flight without touching the compiled step.

The slot cache layout is the model's own: ``max_slots × (sinks + window
| max_len)`` per layer, ring-buffer + pinned sinks when windowed.
Greedy decoding is token-for-token identical to sequential
:func:`models.generate` (the golden parity test,
tests/test_serve_engine.py); temperature sampling uses an independent
per-request key stream (``fold``-free: keys split inside the compiled
step), so it is distribution-identical but not key-stream-identical.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer_lm import TransformerLM, make_decode_cache

__all__ = ["LMEngine", "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS = (128, 512, 2048)


def _jit_cache_size(fn) -> int:
    """Compile count of a jitted callable (-1 if this jax can't say).
    The decode bench asserts steady state holds at ONE decode compile."""
    probe = getattr(fn, "_cache_size", None)
    try:
        return int(probe()) if callable(probe) else -1
    except Exception:
        return -1


class LMEngine:
    """Compiled-program pool + slot cache for continuous batching.

    ``model`` is the TRAINING-mode ``TransformerLM`` (the engine derives
    its own ``decode=True`` clones); ``params`` its trained parameters.
    The engine is not thread-safe by itself — the scheduler serializes
    all calls onto one loop thread.

    Cold start (:mod:`fluxdistributed_tpu.compilation`): ``prewarm=True``
    runs :meth:`warmup` at construction — every bucket's prefill, the
    splice and the all-slot decode step compile before the first request
    instead of inside its latency.  ``aot_dir`` goes further: each
    program is loaded from a serialized on-disk executable when one
    matches this topology + model, else compiled now and serialized for
    the next process (a restarted server skips its whole compile pool).
    """

    def __init__(
        self,
        model: TransformerLM,
        params,
        *,
        max_slots: int = 8,
        max_len: int = 1024,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        prewarm: bool = False,
        aot_dir: str | None = None,
    ):
        if model.moe_every:
            raise ValueError(
                "the serving engine supports dense models only (MoE decode "
                "routes per-token expert dispatch; build the model with "
                "moe_every=0)")
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        if not model.use_rope:
            if model.max_len is None or model.max_len < max_len:
                raise ValueError(
                    f"use_rope=False needs the model's learned positional "
                    f"table to cover the engine's max_len ({max_len}); got "
                    f"model.max_len={model.max_len}")
        # clamp buckets to the cache and always top out AT max_len:
        # without the top bucket, a prompt in (largest bucket, max_len]
        # would be rejected even though the slot cache can hold it
        bl = sorted({int(b) for b in buckets if 0 < int(b) < max_len}
                    | {max_len})
        self.buckets: Tuple[int, ...] = tuple(bl)
        self.max_slots = max_slots
        self.max_len = max_len
        # store weights in the model's COMPUTE dtype once, up front.
        # flax casts f32-stored params to `dtype` inside every apply;
        # generate()'s scan hoists that cast out of its loop, but the
        # engine's per-token step would pay the full-tree cast EVERY
        # step (it dominated the step on CPU).  Pre-casting is the same
        # rounding, applied once — numerics identical, and the resident
        # weight footprint halves for bf16 models.
        self.params = jax.tree.map(
            lambda x: jnp.asarray(
                x, model.dtype if jnp.issubdtype(
                    jnp.asarray(x).dtype, jnp.floating) else None),
            params)
        self.model = model
        # decode=True rejects attn_fn by design (the cache path always
        # uses the dense core — the math is identical for gathered
        # weights); dropout is inference-irrelevant.  ring_slack sizes
        # the windowed ring so BUCKET-PADDED prefill can never evict an
        # in-band real key (pad writes land beyond every real position's
        # reach); _insert then scrubs the pad entries themselves.  The
        # slack needed is the largest possible PAD RUN: a prompt padded
        # to its smallest covering bucket pads by less than the gap to
        # the previous bucket — so dense buckets keep windowed slot
        # caches near sinks+window instead of max_len.
        if model.window is not None:
            gaps = [self.buckets[0]] + [
                b - a for a, b in zip(self.buckets, self.buckets[1:])]
            slack = max(gaps)
        else:
            slack = 0
        #: per-slot per-layer KV rows actually allocated.  For windowed
        #: models this is sinks+window+slack (slack = largest bucket
        #: gap), NOT sinks+window: sparse buckets inflate it.  Pass a
        #: denser bucket ladder to tighten the bound toward the window.
        self.kv_rows_per_slot = (
            max_len if model.window is None
            else min(model.window + model.sinks + slack, max_len))
        self.decode_model = model.clone(
            decode=True, slot_decode=True, attn_fn=None, dropout=0.0,
            ring_slack=slack)
        self.prefill_model = model.clone(
            decode=True, slot_decode=False, attn_fn=None, dropout=0.0,
            ring_slack=slack)
        self.cache = make_decode_cache(self.decode_model, max_slots, max_len)
        # reusable zero template: _prefill never mutates its input, so
        # one template serves every admission
        self._prefill_zero = make_decode_cache(self.prefill_model, 1, max_len)
        # per-slot sampling state lives ON DEVICE between steps — the
        # decode loop's only host traffic is the one token sync the
        # scheduler needs for stop checks and streaming
        self._tok = jnp.zeros((max_slots,), jnp.int32)
        self._temp = jnp.zeros((max_slots,), jnp.float32)
        self._keys = jnp.zeros((max_slots, 2), jnp.uint32)
        self._prefill_jit = jax.jit(self._prefill_impl)
        # donate the carried state (slot cache, tokens, keys): every
        # step/splice REPLACES them, so XLA may update the KV in place
        # instead of copying the whole slot cache each call — at serving
        # scale that copy is the step's largest memory traffic after the
        # weights themselves
        self._insert_jit = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._step_jit = jax.jit(self._step_impl, donate_argnums=(1, 2, 4))
        self._sample1_jit = jax.jit(self._sample)
        # AOT executables keyed by program name (prefill additionally by
        # bucket — one fixed shape each); populated by _load_aot, empty
        # when aot_dir is None so every call falls through to the jits
        self._aot: dict = {}
        if aot_dir:
            self._load_aot(aot_dir)
        if prewarm:
            self.warmup()

    # ---- compiled programs ------------------------------------------------

    def _prefill_impl(self, params, cache0, toks, plen):
        """Whole padded prompt in one parallel pass; returns the filled
        batch-1 cache and the logits at the LAST REAL position (the
        distribution of the first generated token)."""
        logits, mut = self.prefill_model.apply(
            {"params": params, "cache": cache0}, toks, train=False,
            mutable=["cache"],
        )
        last = jax.lax.dynamic_slice_in_dim(logits, plen - 1, 1, axis=1)[:, 0]
        return mut["cache"], last.astype(jnp.float32)

    def _insert_impl(self, big, small, slot, plen):
        """Splice a prefilled batch-1 cache into slot row ``slot``.

        Cursor leaves are set to the TRUE prompt length (the prefill ran
        over the padded bucket, so its own cursor reads bucket, not
        plen); pad K/V entries ride along and are masked/overwritten by
        construction (module docstring).
        """

        def leaf(path, bg, sm):
            name = getattr(path[-1], "key", None)
            if name in ("cache_index", "pos_index"):
                return bg.at[slot].set(jnp.asarray(plen, bg.dtype))
            if name == "slot_pos":
                # scrub PAD ring entries (position >= plen) back to -1
                # ("unwritten, never attendable"): the spliced ring then
                # holds exactly what a batch-1 unpadded prefill of plen
                # tokens would hold — the parity invariant
                return bg.at[slot].set(jnp.where(sm < plen, sm, -1))
            if name in ("cached_k", "cached_v"):
                return bg.at[slot].set(sm[0])
            raise ValueError(f"unknown cache leaf {name!r}")

        return jax.tree_util.tree_map_with_path(leaf, big, small)

    def _sample(self, logits, temp, keys):
        """Greedy/temperature next-token draw, per row.

        Same math as ``models.generate`` (f32 logits / temperature →
        categorical; argmax at temperature 0) but with an independent
        key per row, split inside the compiled program.
        """
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        pairs = jax.vmap(partial(jax.random.split, num=2))(keys)
        new_keys, subs = pairs[:, 0], pairs[:, 1]
        scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
        sampled = jax.vmap(jax.random.categorical)(subs, scaled)
        nxt = jnp.where(temp > 0, sampled.astype(jnp.int32), greedy)
        return nxt, new_keys

    def _step_impl(self, params, cache, tok, temp, keys):
        """One decode step over ALL slots: [S] tokens in, [S] out."""
        logits, mut = self.decode_model.apply(
            {"params": params, "cache": cache}, tok[:, None], train=False,
            mutable=["cache"],
        )
        nxt, new_keys = self._sample(
            logits[:, 0].astype(jnp.float32), temp, keys)
        return mut["cache"], nxt, new_keys

    # ---- cold-start: AOT executables + prewarm ----------------------------

    def _example_args(self, program: str, bucket: int | None = None):
        """Zero-filled arguments with each program's exact shapes — what
        AOT lowering and prewarm both trace/execute against."""
        if program == "prefill":
            return (self.params, self._prefill_zero,
                    jnp.zeros((1, bucket), jnp.int32),
                    jnp.asarray(1, jnp.int32))
        if program == "insert":
            return (self.cache, self._prefill_zero,
                    jnp.asarray(0, jnp.int32), jnp.asarray(1, jnp.int32))
        if program == "step":
            return (self.params, self.cache, self._tok, self._temp, self._keys)
        if program == "sample1":
            return (jnp.zeros((1, self.model.vocab), jnp.float32),
                    jnp.zeros((1,), jnp.float32),
                    jnp.zeros((1, 2), jnp.uint32))
        raise ValueError(f"unknown engine program {program!r}")

    def _load_aot(self, aot_dir: str) -> None:
        """Load-or-compile every engine program as a serialized AOT
        executable under ``aot_dir``.  A process that finds matching
        files on disk skips tracing, lowering AND backend compilation
        for its entire program pool; any mismatch (topology, jaxlib,
        model shape) falls back to a fresh compile of that program,
        which is then serialized for the next process."""
        from .. import compilation

        # everything that changes a compiled program without changing
        # argument shapes (windowing, norms, rope, ...) is in the model
        # repr (config_tag scrubs the addresses a callable field like
        # attn_fn prints); max_len/buckets shape the cache and prefill
        tag = compilation.config_tag(
            repr(self.model), self.max_slots, self.max_len, self.buckets)
        fp = compilation.topology_fingerprint(tag=tag)
        jobs = [("insert", self._insert_jit, None),
                ("step", self._step_jit, None),
                ("sample1", self._sample1_jit, None)]
        jobs += [("prefill", self._prefill_jit, b) for b in self.buckets]
        for name, fn, bucket in jobs:
            args = self._example_args(name, bucket)
            key = (name, bucket) if bucket is not None else name
            fname = f"serve_{name}" + (f"_b{bucket}" if bucket else "")
            self._aot[key] = compilation.load_or_compile(
                fn, args, directory=aot_dir, name=fname, fingerprint=fp)

    def _call_prefill(self, padded, plen):
        fn = self._aot.get(("prefill", int(padded.shape[1])))
        if fn is None:
            fn = self._prefill_jit
        return fn(self.params, self._prefill_zero, padded, plen)

    def _call_insert(self, small, slot, plen):
        fn = self._aot.get("insert", self._insert_jit)
        return fn(self.cache, small, slot, plen)

    def _call_step(self):
        fn = self._aot.get("step", self._step_jit)
        return fn(self.params, self.cache, self._tok, self._temp, self._keys)

    def _call_sample1(self, logits, temp, keys):
        fn = self._aot.get("sample1", self._sample1_jit)
        return fn(logits, temp, keys)

    def warmup(self) -> dict:
        """Pre-pay every compile before the first request: one prefill
        per bucket, one splice, one all-slot decode step, one sample —
        then rebuild pristine slot state, so the warmed engine is
        indistinguishable from a fresh one except that no program
        compiles on the serving path again (the ONE-decode-compile
        invariant holds with the compile moved ahead of traffic).

        Returns ``{"seconds": ..., "compiles": ...}`` (compiles == 0
        when an AOT pool or a warm persistent cache made even warmup
        free of backend compilation... the jit-cache invariant is what
        :meth:`compile_stats` reports either way)."""
        import time

        from ..obs import jaxmon

        jaxmon.install()
        c0 = jaxmon.compile_count()
        t0 = time.perf_counter()
        small = last = None
        for b in self.buckets:
            small, last = self._call_prefill(
                jnp.zeros((1, b), jnp.int32), jnp.asarray(1, jnp.int32))
        self._call_sample1(
            last, jnp.zeros((1,), jnp.float32), jnp.zeros((1, 2), jnp.uint32))
        # the splice and step donate the live slot state; the dummy data
        # they leave behind is discarded with the rebuild below
        self.cache = self._call_insert(
            small, jnp.asarray(0, jnp.int32), jnp.asarray(1, jnp.int32))
        self.cache, self._tok, self._keys = self._call_step()
        jax.block_until_ready(self._tok)
        self.cache = make_decode_cache(
            self.decode_model, self.max_slots, self.max_len)
        self._tok = jnp.zeros((self.max_slots,), jnp.int32)
        self._temp = jnp.zeros((self.max_slots,), jnp.float32)
        self._keys = jnp.zeros((self.max_slots, 2), jnp.uint32)
        return {"seconds": time.perf_counter() - t0,
                "compiles": int(jaxmon.compile_count() - c0)}

    # ---- host-side API (called by the scheduler loop thread) --------------

    def pick_bucket(self, plen: int) -> int:
        """Smallest warm bucket covering ``plen`` (jit caches stay warm)."""
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(
            f"prompt length {plen} exceeds the largest prefill bucket "
            f"({self.buckets[-1]}). Either shorten the prompt or construct "
            f"the engine with a larger bucket (buckets={self.buckets}, "
            f"max_len={self.max_len}).")

    def validate_request(self, prompt_len: int, max_new_tokens: int) -> None:
        """Admission-time shape checks — every error is actionable."""
        if prompt_len < 1:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.pick_bucket(prompt_len)
        if prompt_len + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
                f"= {prompt_len + max_new_tokens} exceeds the engine's slot "
                f"cache (max_len={self.max_len}). Lower max_new_tokens or "
                "rebuild the engine with a larger max_len.")

    def prefill(self, slot: int, tokens: Sequence[int], temperature: float,
                key: np.ndarray):
        """Prefill ``tokens`` into slot ``slot`` and arm its on-device
        sampling state; returns ``(first_token, bucket)``."""
        plen = len(tokens)
        bucket = self.pick_bucket(plen)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = np.asarray(tokens, np.int32)
        small, last = self._call_prefill(
            jnp.asarray(padded), jnp.asarray(plen, jnp.int32))
        self.cache = self._call_insert(
            small, jnp.asarray(slot, jnp.int32), jnp.asarray(plen, jnp.int32))
        nxt, new_key = self._call_sample1(
            last, jnp.asarray([temperature], jnp.float32),
            jnp.asarray(key)[None])
        first = int(np.asarray(nxt)[0])
        self._tok = self._tok.at[slot].set(first)
        self._temp = self._temp.at[slot].set(float(temperature))
        self._keys = self._keys.at[slot].set(new_key[0])
        return first, bucket

    def step_decode(self) -> np.ndarray:
        """One compiled step over all slots; per-slot input tokens, keys
        and temperatures live on device — the only host traffic is the
        returned ``next[S]`` (the scheduler's stop checks/streaming).
        Parked rows compute too; their output is discarded."""
        self.cache, self._tok, self._keys = self._call_step()
        return np.asarray(self._tok)

    def reset_slot(self, slot: int) -> None:
        """Park a freed slot: zero its cursor (so it cannot creep toward
        int32 wraparound across very long serving sessions) and its
        temperature.  Parked slots still ride the compiled step; their
        writes/outputs are masked/discarded."""

        def leaf(path, bg):
            name = getattr(path[-1], "key", None)
            if name in ("cache_index", "pos_index"):
                return bg.at[slot].set(jnp.zeros((), bg.dtype))
            return bg

        self.cache = jax.tree_util.tree_map_with_path(leaf, self.cache)
        self._temp = self._temp.at[slot].set(0.0)

    def compile_stats(self) -> dict:
        """Compile counts per program — the no-recompile steady-state
        assertion reads ``decode_compiles == 1`` after warmup (a
        ``prewarm=True`` engine satisfies it before the first request).
        An AOT engine serves through deserialized executables instead of
        the jits, so its jit cache sizes stay 0 and ``aot_programs``
        reports the loaded pool instead."""
        return {
            "decode_compiles": _jit_cache_size(self._step_jit),
            "prefill_compiles": _jit_cache_size(self._prefill_jit),
            "insert_compiles": _jit_cache_size(self._insert_jit),
            "aot_programs": len(self._aot),
        }
