"""FDT102 negative: pure traced code; monotonic clock in hot paths;
wall clock only on cold host paths."""
import time

import jax


@jax.jit
def pure(x):
    return x * 2


def hot_loop(tracer, items):
    with tracer.span("step"):
        t0 = time.perf_counter()  # monotonic — the sanctioned clock
        for _ in items:
            pass
        return time.perf_counter() - t0


def checkpoint_stamp():
    # cold path, no span bracket: wall-clock timestamps are fine
    return time.time()
