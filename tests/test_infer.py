"""Inference demo: showpreds table + checkpoint round-trip through the CLI.

The reference's inference path is the Pluto notebook (bin/pluto.jl:
BSON.load a trained model :124, preprocess a frame, print top-3 labels
:338-382).  Invariants here: the table ranks by probability, restored
checkpoints reproduce the trainer's predictions exactly, and the CLI
wires preprocess → forward → showpreds end to end.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "bin"))


def test_showpreds_format_and_ranking():
    from fluxdistributed_tpu.ops import showpreds

    logits = np.array([[0.0, 3.0, 1.0], [5.0, 0.0, 0.0]], np.float32)
    out = showpreds(logits, class_names=["cat", "dog", "eel"], k=2,
                    names=["a.jpg", "b.jpg"])
    lines = out.splitlines()
    assert lines[0] == "a.jpg:"
    assert "1. dog" in lines[1] and "2. eel" in lines[2]
    assert "1. cat" in lines[4]
    # probabilities are softmaxed and descending
    p1 = float(lines[1].split()[-1])
    p2 = float(lines[2].split()[-1])
    assert p1 > p2 > 0


# slow tier: subprocess-scale CLI smoke (full vision forward compile);
# the LM CLI smoke (test_generate_cli token mode) keeps CLI coverage fast
@pytest.mark.slow
def test_infer_cli_random_init(capsys):
    import infer

    rc = infer.main(["--model", "resnet18", "--num-classes", "10",
                     "--image-size", "32"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "<synthetic>:" in out and "1. class" in out


def test_infer_cli_checkpoint_roundtrip(tmp_path, capsys):
    """Train 2 steps, checkpoint, infer from the checkpoint on a real
    image file — predictions must match the trainer's own forward."""
    import jax
    from PIL import Image

    import infer
    from fluxdistributed_tpu import mesh as mesh_lib, optim
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.models import SimpleCNN
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.checkpoint import save_checkpoint
    from fluxdistributed_tpu.train.logging import NullLogger

    mesh = mesh_lib.data_mesh(8)
    ds = SyntheticDataset(nsamples=32, nclasses=10, shape=(32, 32, 3))
    # adam: its opt_state structure differs from momentum's — the CLI's
    # target-free restore must not care which optimizer trained the model
    task = prepare_training(
        SimpleCNN(num_classes=10), ds, optim.adam(1e-3),
        mesh=mesh, batch_size=16, cycles=2,
    )
    train(task, print_every=0, eval_every=0, logger=NullLogger())
    ckdir = str(tmp_path / "ck")
    save_checkpoint(task.state, ckdir, int(task.state.step))

    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, (48, 40, 3)).astype(np.uint8)
    imgfile = str(tmp_path / "x.png")
    Image.fromarray(img).save(imgfile)

    rc = infer.main(["--model", "SimpleCNN", "--num-classes", "10",
                     "--checkpoint", ckdir, "--image-size", "32",
                     "--resize", "36", "--topk", "1", imgfile])
    assert rc == 0
    out = capsys.readouterr().out
    assert "restored checkpoint step 2" in out
    assert imgfile + ":" in out

    # cross-check the predicted class against a direct forward pass
    from fluxdistributed_tpu.data.preprocess import preprocess

    x = preprocess(imgfile, crop=32, resize=36)[None]
    variables = {"params": task.state.params, **task.state.model_state}
    logits = task.model.apply(variables, x, train=False)
    want = int(np.argmax(np.asarray(logits)))
    assert f"1. class {want}" in out
