"""Collective communication layer — XLA collectives over ICI/DCN.

This module is the TPU-native replacement for the reference's entire
communication backend, which consists of (reference, SURVEY §2):

* intra-node: per-device grad buffers all resident on one HOST GPU,
  filled by ``copyto!`` DtoD pushes (``markbuffer!``/``getbuffer!``/
  ``_copyto!`` src/ddp_tasks.jl:59-78) and reduced sequentially on the
  host device (``sync_buffer`` src/ddp_tasks.jl:93-109) — a hub
  all-reduce; and
* inter-node: Julia ``Distributed`` serialization over capacity-1
  ``RemoteChannel``s to a hub process (``syncgrads`` src/sync.jl:36-81).

On TPU both collapse into compiled XLA collectives emitted inside the
SPMD program: ``psum``/``pmean`` ride the ICI torus within a slice and
DCN across slices, with no host round-trip and no hub.  These wrappers
are meaningful *inside* ``shard_map`` (where a mesh axis name is in
scope); under plain ``jit`` + sharded inputs, XLA inserts the equivalent
collectives automatically from the sharding annotations.

``None``-leaf tolerance mirrors the reference's handling of ``nothing``
gradients for stateless layers.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax

Pytree = Any

__all__ = ["pmean", "psum", "all_gather", "reduce_scatter", "ppermute_ring"]


def _is_none(x):
    return x is None


def psum(tree: Pytree, axis_name: str) -> Pytree:
    """Tree-wise sum across a mesh axis (``None`` leaves pass through)."""
    return jax.tree.map(
        lambda x: None if x is None else lax.psum(x, axis_name),
        tree,
        is_leaf=_is_none,
    )


def pmean(tree: Pytree, axis_name: str) -> Pytree:
    """Tree-wise mean across a mesh axis.

    This single compiled collective IS the reference's gradient
    averaging: ``sync_buffer``'s accumulate-then-divide
    (src/ddp_tasks.jl:103-106) and ``syncgrads``'s hard-coded ``/4.f0``
    (src/sync.jl:68) both become ``pmean`` over the ``data`` axis, with
    the divisor supplied by the mesh instead of hard-coded.
    """
    return jax.tree.map(
        lambda x: None if x is None else lax.pmean(x, axis_name),
        tree,
        is_leaf=_is_none,
    )


def all_gather(tree: Pytree, axis_name: str, axis: int = 0, tiled: bool = True) -> Pytree:
    """Gather shards from every device along ``axis``."""
    return jax.tree.map(
        lambda x: None if x is None else lax.all_gather(x, axis_name, axis=axis, tiled=tiled),
        tree,
        is_leaf=_is_none,
    )


def reduce_scatter(tree: Pytree, axis_name: str, axis: int = 0) -> Pytree:
    """Sum-reduce then scatter shards along ``axis`` (ZeRO-style grad sync)."""
    return jax.tree.map(
        lambda x: None if x is None else lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True),
        tree,
        is_leaf=_is_none,
    )


def ppermute_ring(x, axis_name: str, shift: int = 1):
    """Rotate shards one hop around the mesh-axis ring.

    Building block for ring attention / ring all-reduce over ICI
    neighbours.
    """
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)
