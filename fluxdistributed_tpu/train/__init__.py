from .logging import ConsoleLogger, Logger, current_logger, with_logger
from .trainer import TrainTask, prepare_training, train
from .checkpoint import latest_step, load_checkpoint, save_checkpoint

__all__ = [
    "ConsoleLogger",
    "Logger",
    "current_logger",
    "with_logger",
    "TrainTask",
    "prepare_training",
    "train",
    "save_checkpoint",
    "load_checkpoint",
    "latest_step",
]
