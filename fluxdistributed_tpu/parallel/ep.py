"""Expert parallelism (MoE): top-1 (Switch) or top-k (GShard-style)
routing with capacity, experts sharded over an ``expert`` mesh axis
(``E // axis_size`` experts hosted per device, batched with ``vmap``).

Net-new scope beyond the reference (SURVEY §2: "EP: NO"), built the
TPU-classic way (Mesh-TF/Switch lineage): tokens are sharded over the
same ``expert`` axis, routing/dispatch build ``(tokens, experts,
capacity)`` one-hots locally, and two ``all_to_all`` collectives move
token activations to their expert's device and back — dense einsums and
static shapes throughout, so XLA keeps everything on the MXU (no
gather/scatter in the hot path).

Semantics:
* ``top_k=1`` (Switch): one expert per token, output scaled by the
  router probability; ``top_k>1`` (GShard lineage): k experts per
  token, later choices queue after earlier ones in each expert's
  capacity, gates normalized to sum to 1;
* per-shard expert capacity ``C = ceil(tokens_per_shard / E *
  capacity_factor * top_k)``; tokens over capacity are DROPPED (output
  zero for that choice) — the documented switch behavior;
* auxiliary load-balance loss ``E * Σ_e f_e · p_e`` (first-choice
  fraction routed × mean router prob), returned for the caller to add
  to the task loss.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Pytree = Any

__all__ = [
    "moe_apply",
    "router_dispatch",
    "router_dispatch_expert_choice",
    "stack_expert_params",
]

# sourced from the device layer's single declaration (lint rule FDT105:
# a re-declared literal drifts silently on rename); re-exported here for
# the callers that import it from the ep module
from ..mesh import EXPERT_AXIS


def stack_expert_params(per_expert: list, mesh: Mesh, axis: str = EXPERT_AXIS) -> Pytree:
    """Stack E per-expert param trees on a leading dim sharded over
    ``axis`` — expert ``g`` lives on device ``g // (E // axis_size)``
    (contiguous blocks of local experts per device)."""
    from ..sharding import stack_on_axis

    return stack_on_axis(per_expert, mesh, axis)


def router_dispatch(
    logits: jnp.ndarray, capacity: int, k: int = 1, normalize: Optional[bool] = None
):
    """Top-``k`` dispatch/combine tensors from router logits.

    ``logits``: (T, E).  Returns ``dispatch`` (T, E, C) {0,1},
    ``combine`` (T, E, C) = dispatch · gate, and the load-balance
    auxiliary loss.  Pure jnp — used identically inside the sharded
    program and by the single-device golden model in tests.

    ``k=1`` is Switch routing (gate = router prob); ``k>1`` is
    GShard-style top-k, where later choices queue after earlier ones in
    each expert's capacity and gates are normalized to sum to 1 across
    the chosen experts (``normalize`` overrides; default ``k > 1``).
    The aux loss always uses first-choice assignment (Switch def.).
    """
    t, e = logits.shape
    dtype = logits.dtype
    if not 1 <= k <= e:
        # past round E the masked probs are all-zero and argmax would
        # silently re-route every token to expert 0
        raise ValueError(f"top-k ({k}) must be in [1, experts ({e})]")
    if normalize is None:
        normalize = k > 1
    # routing math in f32 regardless of compute dtype: a bf16 cumsum
    # saturates at 256, collapsing every later queue position onto slot
    # 255 (silent dispatch corruption for large expert queues)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    masked = probs
    counts = jnp.zeros((e,), jnp.float32)  # queue fill from earlier rounds
    ds, gates = [], []
    first_oh = None
    for _ in range(k):
        expert_idx = jnp.argmax(masked, axis=-1)  # (T,)
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (T, E)
        if first_oh is None:
            first_oh = onehot
        # position of each token in its expert's queue (0-based), offset
        # by tokens already queued there in earlier rounds
        pos = (jnp.cumsum(onehot, axis=0) + counts[None, :]) * onehot - 1.0
        kept = (pos >= 0) & (pos < capacity)
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        ds.append(pos_oh * kept.astype(jnp.float32)[..., None])
        gates.append(jnp.max(probs * onehot, axis=-1))  # (T,) routed prob, f32
        counts = counts + onehot.sum(axis=0)
        masked = masked * (1.0 - onehot)
    if normalize:
        gsum = sum(gates) + 1e-9
        gates = [g / gsum for g in gates]
    dispatch = sum(ds).astype(dtype)
    combine = sum(
        d * g[:, None, None] for d, g in zip(ds, gates)
    ).astype(dtype)
    # load-balance aux: E * Σ_e (fraction of tokens to e) · (mean prob of e)
    frac = first_oh.mean(axis=0)
    mean_p = probs.mean(axis=0)
    aux = e * jnp.sum(frac * mean_p)
    return dispatch, combine, aux


def router_dispatch_expert_choice(logits: jnp.ndarray, capacity: int):
    """Expert-choice dispatch/combine (Zhou et al. 2022): each EXPERT
    picks its top-``capacity`` tokens by router probability, instead of
    tokens picking experts.

    Load balance is perfect by construction (every expert processes
    exactly ``capacity`` token slots), so the aux loss is 0; tokens may
    be processed by several experts or none.  Returns the same
    ``(dispatch (T,E,C), combine, aux)`` contract as ``router_dispatch``.
    """
    t, e = logits.shape
    dtype = logits.dtype
    if capacity > t:
        raise ValueError(
            f"expert-choice capacity ({capacity}) cannot exceed tokens per shard ({t})"
        )
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # (T, E)
    _, idx = jax.lax.top_k(probs.T, capacity)  # (E, C) token ids per expert
    dispatch_f32 = jax.nn.one_hot(idx, t, dtype=jnp.float32).transpose(2, 0, 1)
    combine = (dispatch_f32 * probs[:, :, None]).astype(dtype)
    return dispatch_f32.astype(dtype), combine, jnp.zeros((), jnp.float32)


def moe_apply(
    expert_fn: Callable,
    mesh: Mesh,
    axis: str = EXPERT_AXIS,
    capacity_factor: float = 1.25,
    capacity: Optional[int] = None,
    top_k: int = 1,
    routing: str = "token",
    batch_axis: Optional[str] = None,
    pad_tokens: bool = False,
):
    """Build ``fn(stacked_params, router_w, x) -> (y, aux)``.

    ``x``: (T, D) tokens sharded on ``axis``; ``router_w``: (D, E)
    replicated; ``stacked_params`` leaves (E, ...) sharded on ``axis``.
    E must be a multiple of the ``axis`` size: each device hosts
    ``E // axis_size`` experts (expert ``g`` lives on device ``g // L``,
    matching ``stack_expert_params``'s contiguous sharding).  Output is
    token-sharded like ``x``; ``aux`` is the replicated (pmean-ed)
    load-balance loss.  ``routing`` selects token-choice (``"token"``,
    with ``top_k`` = 1 Switch / >1 GShard-style) or expert-choice
    (``"expert_choice"``: each expert takes its top-C tokens; perfectly
    balanced, aux = 0).

    ``batch_axis`` composes data parallelism on a ``(data, expert)``
    mesh: the token dim is sharded over BOTH axes, each data row routes
    its own tokens among that row's expert shards (expert weights are
    replicated across rows; their gradient all-reduce over ``data`` is
    AD's transpose of that replication), and the dispatch ``all_to_all``
    stays within the row.
    """
    if routing not in ("token", "expert_choice"):
        raise ValueError(f"unknown routing {routing!r}")
    if routing == "expert_choice" and top_k != 1:
        raise ValueError("top_k applies to token-choice routing only")
    if pad_tokens and routing == "expert_choice":
        # pad tokens get uniform router prob 1/E and would displace real
        # tokens from each expert's top-capacity pick
        raise ValueError("pad_tokens is incompatible with expert_choice routing")
    if pad_tokens and capacity is None:
        raise ValueError(
            "pad_tokens=True needs an explicit capacity: the auto capacity "
            "ceil(T/E * factor) is ~1 for tiny decode steps and pad tokens "
            "consume slots — size it for the real token count plus headroom"
        )
    e_devices = mesh.shape[axis]
    tok_spec = P((batch_axis, axis)) if batch_axis else P(axis)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(), tok_spec),
        out_specs=(tok_spec, P()),
    )
    def run(stacked_params, router_w, x):
        t, d = x.shape
        e = router_w.shape[-1]
        s = e_devices  # shards on the expert axis
        assert e % s == 0, (
            f"experts ({e}) must be a multiple of '{axis}' size ({s})"
        )
        loc = e // s  # experts hosted per device
        if capacity is not None:
            if capacity < 1:
                raise ValueError(f"capacity must be >= 1, got {capacity}")
            cap = capacity
        else:
            cap = max(1, math.ceil(t / e * capacity_factor * top_k))
        logits = x @ router_w
        if routing == "expert_choice":
            dispatch, combine, aux = router_dispatch_expert_choice(logits, cap)
        else:
            dispatch, combine, aux = router_dispatch(logits, cap, k=top_k)
        # (T,D),(T,E,C) → (E,C,D): each expert's queue from this shard
        expert_in = jnp.einsum("td,tec->ecd", x, dispatch)
        # exchange: device q receives every shard's queues for its LOC
        # local experts (global expert g = q·LOC + l)
        expert_in = jax.lax.all_to_all(
            expert_in.reshape(s, loc, cap, d), axis,
            split_axis=0, concat_axis=0, tiled=False,
        )  # (S_src, LOC, C, D)
        # per local expert: tokens from all shards, one batched apply
        xin = expert_in.transpose(1, 0, 2, 3).reshape(loc, s * cap, d)
        y = jax.vmap(expert_fn)(stacked_params, xin)  # leaves (LOC, ...)
        y = y.reshape(loc, s, cap, d).transpose(1, 0, 2, 3)  # (S, LOC, C, D)
        # route results back to the token-owning shards
        y = jax.lax.all_to_all(y, axis, split_axis=0, concat_axis=0, tiled=False)
        out = jnp.einsum("ecd,tec->td", y.reshape(e, cap, d), combine)
        aux = jax.lax.pmean(aux, axis)
        if batch_axis:
            aux = jax.lax.pmean(aux, batch_axis)
        return out, aux

    n_shards = e_devices * (mesh.shape[batch_axis] if batch_axis else 1)

    def fn(stacked_params, router_w, x):
        t = x.shape[0]
        pad = (-t) % n_shards
        if pad and not pad_tokens:
            raise ValueError(
                f"token count {t} is not divisible by the mesh's {n_shards} "
                "shards. For training this usually means a batch/mesh "
                "misconfiguration; for small decode steps build the moe_fn "
                "with pad_tokens=True and an explicit capacity"
            )
        if pad:
            # zero tokens route like any other (uniform router prob) and
            # occupy capacity slots + appear in the aux statistics — the
            # explicit-capacity requirement above keeps real tokens safe
            x = jnp.concatenate(
                [x, jnp.zeros((pad, x.shape[1]), x.dtype)], axis=0
            )
        y, aux = run(stacked_params, router_w, x)
        return (y[:t] if pad else y), aux

    return fn
